"""Continuous-batching decode engine on a paged KV cache (ISSUE 3).

The whole-batch path (`generate_tokens`) is synchronous: every request
in a call starts together, the batch runs until the SLOWEST row
finishes, and each row owns a dense (b, g, max_len, d) cache sized to
the worst case. Mixed-length traffic wastes both HBM and decode steps.
This engine is the serving-side alternative, after Ragged Paged
Attention (arxiv 2604.15464) and the slot-level-admission result of the
Gemma-on-TPU serving study (arxiv 2605.25645):

- the cache is a GLOBAL page pool per layer (num_pages, page_size, g,
  d) plus one (slots, max_pages) page table and per-slot lengths
  (models/gpt.py init_paged_kv_caches); HBM holds `page_budget` tokens
  of KV total, not slots * max_len;
- a fixed number of SLOTS decode in lockstep through a jitted
  lax.scan of up to `step_horizon` single-token steps per host
  round-trip (dispatch amortizer; the horizon is clamped to the
  nearest slot completion and pow2-bucketed, so at most
  log2(H)+1 scan lengths x {greedy, mixed} are ever traced) —
  admission, retirement and ragged lengths never recompile anything;
- finished slots retire their pages to a free list and queued requests
  are admitted mid-flight into the free slots;
- admission is CHUNKED by default (ISSUE 4, after Ragged Paged
  Attention's mixed-step result): each scheduler round carries a token
  budget (`prefill_chunk_tokens`) split between ONE resumable prefill
  chunk — a ragged span of the oldest admitting prompt, at a saved
  offset — and a single-token decode row for every other live slot,
  all through one jitted MIXED step (models/attention.py chunked paged
  branch -> ops/prefill_attention.py). A long prompt therefore delays
  each in-flight decode token by at most one budget-bounded chunk
  forward instead of its whole prefill, prompts are never pow2-padded,
  and only one mixed-step trace exists per pow2 width bucket (vs one
  whole-prompt prefill executable per prompt bucket).
  `prefill_chunk_tokens=0` restores whole-prompt admission: a bucketed
  prefill (`bucket_prefill_len` compile shapes, LRU-bounded executable
  cache) writes the prompt's K/V into the slot's pages between decode
  rounds — still the right call for single-tenant short-prompt traffic
  (docs/GUIDE.md "Chunked prefill");
- per-request knobs (tokens_to_generate, greedy/top-k/top-p/
  temperature/seed, logprobs) ride per-slot ARRAYS through the step
  function — they are data, not compile-time statics.

Greedy decode is exact-match with `generate_tokens` for the same
prompt (tests/test_engine.py) in BOTH admission modes and regardless of
where chunk boundaries fall: every position's compute is
row-independent (per-position matmul rows, per-row softmax over the
same masked columns), so chunking the prompt changes op shapes but not
values — the token stream is bitwise identical, and logprobs are
bitwise at matched shapes / within one fp32 ulp when the backend's
matmul thread-blocking differs across chunk widths (the CPU test
harness's virtual-device split does this); the paged XLA fallback
gathers pages into the same dense view the dense path reads.

Scheduling is host-driven (one device scan per loop iteration) because
admission IS a host decision; the dense engine's while_loop stays the
right tool for single-shot batch eval (docs/GUIDE.md, "when the dense
kernel still wins").

ISSUE 6 adds three compounding serving features on the same pool:

- **Prefix sharing** (`prefix_cache=True`, inference/prefix_cache.py):
  admission looks the prompt up in a refcounted page-aligned prefix
  index and maps cache-hit pages into the slot's page table instead of
  re-prefilling them — chunked prefill resumes at the first uncached
  token (mid-page divergence rides a copy-on-write page copy). Pages
  free-list only at refcount zero; unreferenced cached prefixes evict
  LRU under pool pressure. Requires chunked admission (the suffix
  prefill must attend to pooled context, which the whole-prompt dense
  prefill cannot).
- **Token streaming** (`submit(..., stream=True)`): every generated
  token is pushed to a per-request queue as it is booked, closed with a
  None sentinel at completion/failure — the HTTP layer's SSE feed
  (inference/server.py). `cancel()` retires an abandoned request's slot
  mid-flight and reclaims its pages (refcounts intact).
- **Speculative decoding** (`spec_decode_k>0`): a prompt-lookup n-gram
  drafter proposes up to k tokens per greedy slot; one width-(k+1)
  ragged chunk per slot verifies them (the prefill kernel's
  arbitrary-start chunks ARE the verification shape). Accepted runs
  keep bitwise greedy parity — every emitted token is the same
  `_greedy_pick` the decode scan would have made; rejection rolls the
  slot's host-authoritative length back, so stale K/V past the accepted
  position is overwritten by the next round's writes and never read
  (the kernels mask by length).

ISSUE 9 quantizes the serving hot path, both bandwidth levers at once:
`kv_dtype="int8"` stores the page pools as int8 with per-(token, group)
fp32 scale pools riding every jitted step beside the data (quantize at
scatter, dequantize in-register — ops/quantization.py is the ONE
convention; COW page copies and null-page routing carry scales with
their pages, and the host-side refcount/eviction accounting never sees
a dtype), and `quantize_weights=True` swaps the decode GEMV weights for
one-shot weight-only int8. Both default OFF: the fp path keeps its
bitwise generate_tokens parity; the int8 path's accuracy is a measured
drift bound (bench `extra.quant`, docs/GUIDE.md "Quantized serving").

ISSUE 14 grows the engine a mesh axis and a fleet: `serving_tp > 1`
shards the page pools (and scale pools) over the head/group axis and
runs every jitted step — decode scan, mixed step, spec verify, prefill
buckets, COW page copy — under pjit on a tp mesh via GSPMD constraints
(kv_pool_spec / decode_param_specs, parallel/sharding.py), with page
tables, lengths and the per-slot sampling arrays replicated; the Pallas
paged kernels already read per-(group) blocks, so each shard runs them
over its own groups with the XLA twins as the CPU oracle. N such
engines (each tagged `replica_id`, optionally pinned to a `devices`
subset) sit behind the prefix-affinity router (inference/router.py),
which dispatches shared-prefix traffic to the replica whose PrefixCache
already holds the pages and falls back least-loaded.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.analysis.contracts import (
    compile_contract,
    release_variant,
)
from megatron_llm_tpu.inference.generation import bucket_prefill_len
from megatron_llm_tpu.inference.prefix_cache import PrefixCache
from megatron_llm_tpu.inference.sampling import (
    NEG_INF,
    modify_logits_for_top_p,
)
from megatron_llm_tpu.telemetry import (
    NULL_TRACER,
    FlightRecorder,
    Histogram,
    SpanTracer,
    render_prometheus,
)

_logger = logging.getLogger(__name__)


def horizon_buckets(step_horizon: int) -> list:
    """The pow2 decode-scan horizons an engine with this step_horizon
    can ever dispatch: {1, 2, 4, ..., pow2floor(step_horizon)}. ONE
    definition shared by warmup(), the contract budget, and the audit —
    the claim 'at most log2(H)+1 scan lengths trace' is enforced, not
    asserted in prose."""
    top = 1 << (max(step_horizon, 1).bit_length() - 1)
    out, h = [], 1
    while h <= top:
        out.append(h)
        h *= 2
    return out


def mixed_width_buckets(prefill_chunk_tokens: int) -> list:
    """The mixed-step chunk widths _chunk_width can ever return: every
    pow2 below the budget plus the budget itself — log2(C)+1 buckets.
    Shared by warmup(), the contract budget, and the audit."""
    c = prefill_chunk_tokens
    if c <= 0:
        return []
    widths = {c}
    w = 1
    while w < c:
        widths.add(w)
        w *= 2
    return sorted(widths)


class QueueFull(RuntimeError):
    """Raised by submit() when the admission queue is at capacity; the
    HTTP layer maps it to 503 + Retry-After."""


def _greedy_pick(last_logits, vocab_size):
    """The greedy-specialized token decision — argmax on the
    vocab-clamped logits, no per-row sort machinery. ONE definition
    shared by the decode-scan and mixed-step builders: the engine's
    tokens must be independent of which step flavor served them, so the
    two paths may never drift numerically."""
    l = last_logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < l.shape[-1]:
        pad = jnp.arange(l.shape[-1]) >= vocab_size
        l = jnp.where(pad[None, :], NEG_INF, l)
    return jnp.argmax(l, axis=-1).astype(jnp.int32)


def _per_slot_sample(logits, greedy, temperature, top_k, top_p, seeds,
                     steps, vocab_size):
    """One sampling decision per SLOT with per-slot knobs as traced
    arrays (the whole-batch `sample` takes them as jit statics — a
    continuous batch mixes them freely, so they must be data here).
    top-k/top-p reproduce inference/sampling.py semantics, including the
    top-p shift-by-1, via one shared descending sort; greedy rows ignore
    the sampled value. RNG: per-request seed folded with the request's
    own sampling-step count, so a request's stream is independent of
    which slot it landed in and of its neighbours."""
    V = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < V:
        pad = jnp.arange(V) >= vocab_size
        logits = jnp.where(pad[None, :], NEG_INF, logits)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    l = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # per-row top-k: the kth DESCENDING-sorted value is the row's
    # threshold (modify_logits_for_top_k needs a static k; the threshold
    # form is its per-row generalization)
    sorted_l = jnp.sort(l, axis=-1)[:, ::-1]
    kth_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_l, kth_idx[:, None], axis=-1)
    l = jnp.where((top_k > 1)[:, None] & (l < kth), NEG_INF, l)
    # per-row top-p through the ONE reference implementation
    # (sampling.modify_logits_for_top_p broadcasts a (rows, 1)
    # threshold); rows with top_p == 0 keep their logits untouched
    filt = modify_logits_for_top_p(l, top_p[:, None])
    l = jnp.where((top_p > 0.0)[:, None], filt, l)

    keys = jax.vmap(
        lambda s, t: jax.random.fold_in(jax.random.key(s), t)
    )(seeds, steps)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row)
    )(keys, l).astype(jnp.int32)
    return jnp.where(greedy, greedy_tok, sampled)


@dataclass
class EngineRequest:
    """One queued/running generation. `tokens` grows to prompt +
    generated; `log_probs[i]` (when requested) is
    log P(tokens[i+1] | tokens[:i+1]) — the generate_tokens layout."""

    rid: int
    prompt: List[int]
    tokens_to_generate: int
    # which replica's engine owns this request (ISSUE 14): None on a
    # standalone engine; the router routes cancel() by it and the SSE
    # `id:` field carries it so N replicas' rids stay distinguishable
    replica_id: Optional[int] = None
    greedy: bool = True
    top_k: int = 0
    top_p: float = 0.0
    temperature: float = 1.0
    seed: int = 0
    return_log_probs: bool = False
    use_eod_for_early_termination: bool = True
    # per-request wall-clock budget from submit(); None = no deadline.
    # Enforced by the scheduler each round: an expired request fails its
    # waiter with TimeoutError and RETIRES its slot — the pages go back
    # to the pool instead of being held by a client that gave up.
    deadline_s: Optional[float] = None

    # streaming: when submit(stream=True), every GENERATED token id is
    # put here as it is booked; a None sentinel closes the stream at
    # completion, failure, timeout, or cancel (the SSE layer's feed)
    stream_q: Optional["queue_mod.SimpleQueue"] = None
    # set by DecodeEngine.cancel() (e.g. the HTTP client disconnected
    # mid-stream); the scheduler reaps it next round — queued requests
    # fail immediately, running slots retire and reclaim their pages
    cancelled: bool = False

    tokens: List[int] = field(default_factory=list)
    log_probs: List[float] = field(default_factory=list)
    error: Optional[str] = None
    timed_out: bool = False
    done: threading.Event = field(default_factory=threading.Event)
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0  # first GENERATED token (TTFT = t_first - t_submit)
    t_done: float = 0.0

    def expired(self, now: float) -> bool:
        return (self.deadline_s is not None
                and now - self.t_submit > self.deadline_s)

    def result(self, timeout: Optional[float] = None):
        """Block until the request finishes; returns (tokens, log_probs).
        A request that blew its `deadline_s` raises TimeoutError (the
        engine already reclaimed its slot/pages); other engine failures
        raise RuntimeError."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.rid} still running")
        if self.error is not None:
            if self.timed_out:
                raise TimeoutError(self.error)
            raise RuntimeError(self.error)
        return self.tokens, (self.log_probs if self.return_log_probs
                             else None)


@dataclass
class _Slot:
    req: Optional[EngineRequest] = None
    pages: List[int] = field(default_factory=list)
    forced: collections.deque = field(default_factory=collections.deque)
    generated: int = 0
    sample_step: int = 0
    # chunked admission: next prompt position to prefill (the resumable
    # saved offset); == len(req.prompt) once prefill is complete.
    # Prefix sharing starts it at the matched-token count: cache-hit
    # positions never prefill.
    prefill_pos: int = 0
    # prefix cache: how many full prompt pages of this slot are already
    # registered (or were mapped shared at admission); registration
    # advances as prefill passes each page boundary
    registered: int = 0
    # speculative drafting: bigram -> up to the 8 most recent start
    # indices in req.tokens, maintained INCREMENTALLY (amortized O(1)
    # per booked token — a per-round rescan of a long history would
    # erode the latency spec decoding buys). Multiple occurrences are
    # kept because on short-period repetition the NEWEST one sits at
    # the sequence tail with an empty continuation — an older one is
    # what actually drafts. `bigram_next` is the next start index to
    # fold in; the FINAL bigram stays unindexed so a lookup never
    # matches the occurrence it is extending.
    bigram: dict = field(default_factory=dict)
    bigram_next: int = 0
    # per-request device-cost accounting (ISSUE 15, cost_registry on):
    # the scheduler round this slot admitted at, the prompt offset
    # prefill started from (cache-hit positions never compute), the
    # prompt tokens actually prefilled on device, and the draft tokens
    # spec-decode booked for this request — the retire event's cost
    # record is assembled from exactly these host counters
    admit_round: int = 0
    prefill_start: int = 0
    prefilled: int = 0
    spec_accepted: int = 0
    # sliding-window serving (ISSUE 19): logical page frontier counters.
    # `mapped` — logical pages [reclaimed, mapped) hold physical pages
    # (windowed slots allocate lazily and top up just before each round
    # writes past the frontier); `reclaimed` — logical pages [0,
    # reclaimed) fell wholly out of every live window and were released
    # (table entries parked on null page 0). pages[k] is the physical
    # page at logical index reclaimed + k.
    mapped: int = 0
    reclaimed: int = 0

    @property
    def prefilling(self) -> bool:
        return self.req is not None and self.prefill_pos < len(
            self.req.prompt)


@compile_contract(
    "engine.decode_scan",
    max_variants=16,  # 2 specializations x (log2(horizon)+1) pow2 buckets
    collectives={"single": frozenset(),
                 # tp2 (ISSUE 14): all-reduce = the row-parallel wo/w2
                 # partial sums and the vocab-sharded embedding/head/
                 # argmax reductions; all-gather = the carried
                 # last_logits re-replicating each scan step (the
                 # carry is a REPLICATED per-slot operand by design —
                 # the host reads tokens from it and sampling sorts
                 # it whole). reduce-scatter would be a resharding
                 # leak and fails the audit.
                 "tp2": frozenset({"all-reduce", "all-gather"})},
    tmp_bytes_budget=1 << 20,
    notes="pow2-bucketed scan horizons x {greedy, mixed}; the engine "
          "passes the config-derived budget "
          "2*len(horizon_buckets(step_horizon)) at mint time; kv_dtype "
          "is an engine-level choice, never a new variant key; so is "
          "attention_window_size (ISSUE 19) — the window bakes into "
          "the model config at trace time and page reclamation is host "
          "bookkeeping, zero new executables")
def _make_step_fn(model, vocab_size, horizon, all_greedy):
    """The jitted continuous-batching step, traced once per (engine,
    horizon bucket): a lax.scan of `horizon` single-token steps — each
    samples/teacher-forces one token per slot from the carried logits
    and runs it through the paged stack (scatter K/V into each slot's
    current page, paged attention over owned pages). Batching HORIZON
    steps per host round-trip amortizes dispatch latency (on the axon
    tunnel one dispatch can cost more than the step itself); the host
    clamps the horizon to the nearest slot completion, so no request
    ever overruns its budget inside a horizon. Page pools are donated —
    the update is in place. Int8 engines (ISSUE 9) pass the fp32 scale
    pools as pools_ks/pools_vs (donated, updated alongside the data in
    the scan carry); fp engines pass empty tuples and trace the same
    program they always did."""

    def step(dec_params, pools_k, pools_v, pools_ks, pools_vs,
             page_table, lengths, last_logits, active, forced,
             use_forced, greedy, temperature, top_k, top_p, seeds,
             sample_steps):
        # forced/use_forced: (slots, horizon) — the remaining prompt
        # tokens are known in advance, so teacher forcing rides the scan
        quant = len(pools_ks) > 0  # int8 pools carry scale pools

        def body(carry, xs):
            pools_k, pools_v, pools_ks, pools_vs, lengths, last_logits, \
                steps_c = carry
            forced_t, use_forced_t = xs
            lp_full = jax.nn.log_softmax(
                last_logits.astype(jnp.float32), axis=-1)
            if all_greedy:
                # every live request is greedy (the serving-bench hot
                # path): the per-row sort/cumsum machinery of the
                # sampled branch would cost a full (slots, V) sort per
                # token for nothing
                sampled = _greedy_pick(last_logits, vocab_size)
            else:
                sampled = _per_slot_sample(
                    last_logits, greedy, temperature, top_k, top_p,
                    seeds, steps_c, vocab_size)
            chosen = jnp.where(use_forced_t, forced_t, sampled)
            chosen = jnp.where(active, chosen, 0)
            chosen_lp = jnp.take_along_axis(
                lp_full, chosen[:, None].astype(jnp.int32), axis=-1)[:, 0]
            caches = {"k_pages_layers": pools_k,
                      "v_pages_layers": pools_v,
                      "page_table": page_table, "lengths": lengths}
            if quant:
                caches["k_scales_layers"] = pools_ks
                caches["v_scales_layers"] = pools_vs
            logits, new_caches = model.forward(
                dec_params, chosen[:, None], kv_caches=caches,
                position_ids=lengths[:, None],
            )
            steps_c = steps_c + (active & ~use_forced_t)
            # carry the logits at last_logits' dtype (fp32): a bf16-
            # compute model would otherwise flip the scan carry dtype
            # on the first step and fail trace (no-op for fp32 models,
            # so the bitwise-parity engines are untouched)
            return ((new_caches["k_pages_layers"],
                     new_caches["v_pages_layers"],
                     new_caches.get("k_scales_layers", ()),
                     new_caches.get("v_scales_layers", ()),
                     new_caches["lengths"],
                     logits[:, 0].astype(last_logits.dtype), steps_c),
                    (chosen, chosen_lp))

        carry = (pools_k, pools_v, pools_ks, pools_vs, lengths,
                 last_logits, sample_steps)
        carry, (chosen_h, lp_h) = jax.lax.scan(
            body, carry, (forced.T, use_forced.T))
        pools_k, pools_v, pools_ks, pools_vs, _, last_logits, _ = carry
        # (horizon, slots) -> (slots, horizon)
        return (chosen_h.T, lp_h.T, last_logits, pools_k, pools_v,
                pools_ks, pools_vs)

    return jax.jit(step, donate_argnums=(1, 2, 3, 4))


@compile_contract(
    "engine.mixed_step",
    max_variants=24,  # 2 specializations x (log2(chunk budget)+1) widths
    collectives={"single": frozenset(),
                 "tp2": frozenset({"all-reduce"})},  # see decode_scan
    tmp_bytes_budget=4 << 20,
    notes="pow2 chunk-width buckets x {greedy, mixed}; the engine "
          "passes 2*len(mixed_width_buckets(prefill_chunk_tokens)) "
          "at mint time; attention_window_size is engine-static like "
          "kv_dtype (see decode_scan) — windowed engines mint the "
          "same width buckets, never a window-keyed variant")
def _make_mixed_step_fn(model, vocab_size, width, all_greedy):
    """The jitted MIXED prefill+decode step (chunked admission), traced
    once per (engine, pow2 width bucket, greedy specialization): every
    slot contributes one ragged span through the chunked paged stack —
    the admitting slot a prefill chunk of up to `width` prompt tokens at
    its saved offset, each decoding slot a single sampled/greedy token,
    idle slots nothing (chunk_lens 0) — and attention for all of them
    runs in ONE ragged paged pass (ops/prefill_attention.py). Decode
    rows sample from the carried last_logits BEFORE the forward, exactly
    like the decode scan body, so tokens and logprobs are independent of
    which step flavor served them. Page pools are donated — the update
    is in place.

    Returns per-slot (first token, its logprob under last_logits),
    the CHUNK slot's in-chunk logprobs [lp of chunk token p+1 at p],
    the new last logits, and the pools. last_logits is PRESERVED for
    idle slots."""

    def step(dec_params, pools_k, pools_v, pools_ks, pools_vs,
             page_table, lengths, last_logits, chunk_tokens, chunk_lens,
             is_prefill, chunk_idx, greedy, temperature, top_k, top_p,
             seeds, sample_steps):
        active = chunk_lens > 0
        lp_full = jax.nn.log_softmax(
            last_logits.astype(jnp.float32), axis=-1)
        if all_greedy:
            sampled = _greedy_pick(last_logits, vocab_size)
        else:
            sampled = _per_slot_sample(
                last_logits, greedy, temperature, top_k, top_p, seeds,
                sample_steps, vocab_size)
        first = jnp.where(is_prefill, chunk_tokens[:, 0], sampled)
        first = jnp.where(active, first, 0)
        first_lp = jnp.take_along_axis(
            lp_full, first[:, None].astype(jnp.int32), axis=-1)[:, 0]
        toks = chunk_tokens.at[:, 0].set(first)
        caches = {"k_pages_layers": pools_k, "v_pages_layers": pools_v,
                  "page_table": page_table, "lengths": lengths,
                  "chunk_lens": chunk_lens}
        if len(pools_ks) > 0:  # int8 pools carry scale pools
            caches["k_scales_layers"] = pools_ks
            caches["v_scales_layers"] = pools_vs
        logits, new_caches = model.forward(
            dec_params, toks, kv_caches=caches,
            position_ids=lengths[:, None] + jnp.arange(width)[None, :],
        )
        if width > 1:
            # lp of chunk token p+1 under the logits at p — the prompt-
            # logprob stream of a prefill chunk (position p's target is
            # the NEXT prompt token; the chunk's last target arrives
            # next round via first_lp, the decode scan's layout). Only
            # the ONE prefill chunk row ever needs this, so slice it
            # out before the (width, V) log_softmax instead of paying a
            # (slots, width, V) one on every mixed round — these are
            # exactly the rounds the decode-interference gauge watches.
            row_logits = logits[chunk_idx, :-1]
            lp_in = jax.nn.log_softmax(
                row_logits.astype(jnp.float32), axis=-1)
            row_toks = jax.lax.dynamic_index_in_dim(
                toks, chunk_idx, 0, keepdims=False)[1:]
            chunk_lps = jnp.take_along_axis(
                lp_in, row_toks[:, None].astype(jnp.int32), axis=-1)[:, 0]
        else:
            chunk_lps = jnp.zeros((0,), jnp.float32)
        last_idx = jnp.clip(chunk_lens - 1, 0, width - 1)
        new_last = jnp.take_along_axis(
            logits, last_idx[:, None, None], axis=1)[:, 0]
        # keep last_logits' dtype (fp32; bf16-compute models upcast
        # here — no-op for fp32 models)
        new_last = jnp.where(active[:, None],
                             new_last.astype(last_logits.dtype),
                             last_logits)
        return (first, first_lp, chunk_lps, new_last,
                new_caches["k_pages_layers"],
                new_caches["v_pages_layers"],
                new_caches.get("k_scales_layers", ()),
                new_caches.get("v_scales_layers", ()))

    return jax.jit(step, donate_argnums=(1, 2, 3, 4))


@compile_contract(
    "engine.prefill_bucket",
    max_variants=8,  # == DecodeEngine._PREFILL_CACHE_CAP: the LRU
    # eviction path release_variant()s, so the live count IS the cache
    collectives={"single": frozenset(),
                 "tp2": frozenset({"all-reduce"})},  # see decode_scan
    tmp_bytes_budget=8 << 20,
    notes="whole-prompt mode only; one executable per prefill bucket, "
          "LRU-bounded — eviction releases the variant")
def _make_prefill_fn(model, prefill_len, page_size):
    """Bucketed prefill, traced once per bucket: one causal forward over
    the prompt's bucket prefix through dense per-layer caches, whose
    K/V rows are scattered STRAIGHT into the slot's pool pages inside
    the same jitted program (XLA fuses the relayout with the cache
    write). Int8 pools quantize each (token, group) row at the same
    scatter (the dense prefill math itself stays fp — quantization is a
    storage decision, ops/quantization.py). Returns updated pools, the
    slot's next-token logits, and the prompt logprobs of the prefix."""

    def prefill(dec_params, pools_k, pools_v, pools_ks, pools_vs,
                tokens, pt_row):
        quant = len(pools_ks) > 0
        caches = model.init_kv_caches(1, prefill_len, layout="layers")
        logits, caches = model.forward(dec_params, tokens,
                                       kv_caches=caches)
        lp = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
        prompt_lp = jnp.take_along_axis(
            lp[:-1], tokens[0, 1:, None].astype(jnp.int32), axis=-1)[:, 0]
        pos = jnp.arange(prefill_len)
        pages = pt_row[pos // page_size]
        offs = pos % page_size
        if quant:
            # quantize-at-write through the ONE shared definition —
            # the same rounding/scale convention as the chunked and
            # decode scatter paths (ops/quantization.py)
            from megatron_llm_tpu.ops.quantization import (
                scatter_quantized_rows,
            )

            new_k, new_v, new_ks, new_vs = [], [], [], []
            for pk, pv, pks, pvs, kl, vl in zip(
                    pools_k, pools_v, pools_ks, pools_vs,
                    caches["k_layers"], caches["v_layers"]):
                pk, pks = scatter_quantized_rows(
                    pk, pks, pages, offs, kl[0].transpose(1, 0, 2))
                pv, pvs = scatter_quantized_rows(
                    pv, pvs, pages, offs, vl[0].transpose(1, 0, 2))
                new_k.append(pk)
                new_v.append(pv)
                new_ks.append(pks)
                new_vs.append(pvs)
            return (tuple(new_k), tuple(new_v), tuple(new_ks),
                    tuple(new_vs), logits[0, -1], prompt_lp)
        pools_k = tuple(
            pk.at[pages, offs].set(kl[0].transpose(1, 0, 2))
            for pk, kl in zip(pools_k, caches["k_layers"]))
        pools_v = tuple(
            pv.at[pages, offs].set(vl[0].transpose(1, 0, 2))
            for pv, vl in zip(pools_v, caches["v_layers"]))
        return pools_k, pools_v, (), (), logits[0, -1], prompt_lp

    return jax.jit(prefill, donate_argnums=(1, 2, 3, 4))


@compile_contract(
    "engine.spec_verify",
    max_variants=2,  # ONE width (spec_decode_k+1) x {greedy, mixed}
    collectives={"single": frozenset(),
                 # all-gather: the replicated last_logits carry +
                 # per-position greedy targets the host books — see
                 # decode_scan
                 "tp2": frozenset({"all-reduce", "all-gather"})},
    tmp_bytes_budget=4 << 20,
    notes="all spec traffic verifies through width spec_decode_k+1; "
          "shorter drafts pad via chunk_lens — per-draft-length buckets "
          "are a contract violation (tests/test_spec_decode.py)")
def _make_spec_step_fn(model, vocab_size, width, all_greedy):
    """The jitted SPECULATIVE verification step, traced once per
    (engine, width = spec_decode_k + 1, greedy specialization): every
    live slot contributes one ragged chunk through the chunked paged
    stack — a spec slot's chunk is [its next token (decided from the
    carried last_logits exactly like a decode row), then its draft
    tokens], a non-spec slot a plain width-1 decode row. The forward
    writes K/V for every chunk position and returns logits per
    position; verification is ON DEVICE: the greedy target at chunk
    position j (`_greedy_pick`, the ONE token-decision definition) is
    compared with the draft at position j+1, and the accepted count is
    the leading run of matches. The carried logits come from the
    ACCEPTED position — so a rejection "rolls back" by simply not
    advancing past it; the host mirrors lengths to first+accepted and
    the next round's writes overwrite the stale K/V (never read: the
    kernels mask by length). Every emitted token is bitwise the token
    the decode scan would have produced, because both paths share
    `_greedy_pick` and per-position compute is row-independent.

    Returns per-slot (first token, its logprob), the per-position
    greedy targets + their logprobs (the accepted tokens' stream
    values), the accepted counts, the new last logits (preserved for
    idle slots), and the donated pools."""

    def step(dec_params, pools_k, pools_v, pools_ks, pools_vs,
             page_table, lengths, last_logits, chunk_tokens, chunk_lens,
             is_spec, greedy, temperature, top_k, top_p, seeds,
             sample_steps):
        active = chunk_lens > 0
        lp_full = jax.nn.log_softmax(
            last_logits.astype(jnp.float32), axis=-1)
        if all_greedy:
            sampled = _greedy_pick(last_logits, vocab_size)
        else:
            sampled = _per_slot_sample(
                last_logits, greedy, temperature, top_k, top_p, seeds,
                sample_steps, vocab_size)
        first = jnp.where(active, sampled, 0)
        first_lp = jnp.take_along_axis(
            lp_full, first[:, None].astype(jnp.int32), axis=-1)[:, 0]
        toks = chunk_tokens.at[:, 0].set(first)
        caches = {"k_pages_layers": pools_k, "v_pages_layers": pools_v,
                  "page_table": page_table, "lengths": lengths,
                  "chunk_lens": chunk_lens}
        if len(pools_ks) > 0:  # int8 pools carry scale pools
            caches["k_scales_layers"] = pools_ks
            caches["v_scales_layers"] = pools_vs
        logits, new_caches = model.forward(
            dec_params, toks, kv_caches=caches,
            position_ids=lengths[:, None] + jnp.arange(width)[None, :],
        )
        n = logits.shape[0]
        V = logits.shape[-1]
        gt = _greedy_pick(logits.reshape(n * width, V),
                          vocab_size).reshape(n, width)
        glp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        gt_lp = jnp.take_along_axis(
            glp, gt[..., None].astype(jnp.int32), axis=-1)[..., 0]
        # accepted run: draft at position j+1 matches the greedy target
        # of position j, leading matches only, within the chunk's valid
        # length
        pos = jnp.arange(1, width)[None, :]
        matches = (toks[:, 1:] == gt[:, :-1]) & (pos < chunk_lens[:, None])
        acc = jnp.sum(jnp.cumprod(matches.astype(jnp.int32), axis=1),
                      axis=1)
        acc = jnp.where(is_spec, acc, 0)
        last_idx = jnp.where(
            is_spec, acc, jnp.clip(chunk_lens - 1, 0, width - 1))
        new_last = jnp.take_along_axis(
            logits, last_idx[:, None, None], axis=1)[:, 0]
        new_last = jnp.where(active[:, None],
                             new_last.astype(last_logits.dtype),
                             last_logits)
        return (first, first_lp, gt, gt_lp, acc, new_last,
                new_caches["k_pages_layers"],
                new_caches["v_pages_layers"],
                new_caches.get("k_scales_layers", ()),
                new_caches.get("v_scales_layers", ()))

    return jax.jit(step, donate_argnums=(1, 2, 3, 4))


@compile_contract(
    "engine.page_copy",
    max_variants=1,  # src/dst are traced scalars: ONE executable ever
    collectives={"single": frozenset(),
                 # tp2: copies are shard-local (the pages axis is
                 # unsharded; each chip copies its own group slice) —
                 # ZERO collectives, pinned
                 "tp2": frozenset()},
    tmp_bytes_budget=1 << 20,
    notes="the prefix cache's COW copy; a second variant would mean "
          "src/dst leaked into the static signature")
def _make_page_copy_fn():
    """One jitted whole-page pool copy (the prefix cache's
    copy-on-write): page `dst` becomes a private replica of shared page
    `src` across every layer's K and V pool — AND, on an int8 engine,
    across every layer's scale pool: a quantized page's KV is the
    (data, scale) pair, and copying one without the other would
    dequantize the replica against a foreign scale. src/dst are traced
    scalars — one executable serves every COW. The read-before-write
    data dependency orders it against any later scatter into `dst`."""

    def copy(pools_k, pools_v, pools_ks, pools_vs, src, dst):
        pools_k = tuple(pk.at[dst].set(pk[src]) for pk in pools_k)
        pools_v = tuple(pv.at[dst].set(pv[src]) for pv in pools_v)
        pools_ks = tuple(ps.at[dst].set(ps[src]) for ps in pools_ks)
        pools_vs = tuple(ps.at[dst].set(ps[src]) for ps in pools_vs)
        return pools_k, pools_v, pools_ks, pools_vs

    return jax.jit(copy, donate_argnums=(0, 1, 2, 3))


@compile_contract(
    "engine.page_export",
    max_variants=1,  # ids is a traced fixed-width vector: ONE executable
    collectives={"single": frozenset(),
                 # tp2: the pages axis is unsharded, so each chip
                 # gathers its own group slice of every requested page —
                 # ZERO collectives, pinned (a collective here would
                 # mean the export resharded the pool)
                 "tp2": frozenset()},
    tmp_bytes_budget=1 << 20,
    notes="disaggregated serving's donor-side page gather (ISSUE 17): "
          "ids is padded to max_pages_per_slot with the null page, so "
          "prefix length can never leak into the static signature")
def _make_page_export_fn():
    """One jitted batched whole-page gather (the donor half of the
    cross-replica KV hand-off): rows `ids` of every layer's K and V
    pool — AND, on an int8 engine, of every layer's scale pool — are
    pulled into dense (max_pages_per_slot, ...) row blocks the host can
    device_get and ship. `ids` is a fixed-width int32 vector padded
    with the null page 0, so one executable serves every prefix length;
    pad rows gather the dead page and are sliced off on the host.
    Pools are NOT donated: an export is a read, and the donor keeps
    serving from the same buffers."""

    def export(pools_k, pools_v, pools_ks, pools_vs, ids):
        rows_k = tuple(pk[ids] for pk in pools_k)
        rows_v = tuple(pv[ids] for pv in pools_v)
        rows_ks = tuple(ps[ids] for ps in pools_ks)
        rows_vs = tuple(ps[ids] for ps in pools_vs)
        return rows_k, rows_v, rows_ks, rows_vs

    return jax.jit(export)


@compile_contract(
    "engine.page_import",
    max_variants=1,  # same fixed-width ids idiom as the export
    collectives={"single": frozenset(),
                 # tp2: the replicated payload rows scatter into each
                 # chip's own group slice of the page pools — ZERO
                 # collectives, pinned, same argument as page_copy
                 "tp2": frozenset()},
    tmp_bytes_budget=1 << 20,
    notes="disaggregated serving's receiver-side page scatter "
          "(ISSUE 17): fixed-width ids padded with the null page; pad "
          "rows scatter zeros into dead page 0, which is dead by the "
          "null-page invariant")
def _make_page_import_fn():
    """One jitted batched whole-page scatter (the receiver half of the
    cross-replica KV hand-off): payload row blocks land at rows `ids`
    of every layer's K/V pool — and of every layer's scale pool on an
    int8 engine, because a quantized page's KV is the (data, scale)
    pair and splitting them would dequantize against a foreign scale.
    `ids` is the same fixed-width null-padded vector the export uses;
    pad rows carry zeros into the dead null page 0, which no page-table
    row maps for reads. Pools are donated — the splice is in place,
    exactly like page_copy."""

    def imp(pools_k, pools_v, pools_ks, pools_vs, ids,
            rows_k, rows_v, rows_ks, rows_vs):
        pools_k = tuple(pk.at[ids].set(rk)
                        for pk, rk in zip(pools_k, rows_k))
        pools_v = tuple(pv.at[ids].set(rv)
                        for pv, rv in zip(pools_v, rows_v))
        pools_ks = tuple(ps.at[ids].set(rs)
                         for ps, rs in zip(pools_ks, rows_ks))
        pools_vs = tuple(ps.at[ids].set(rs)
                         for ps, rs in zip(pools_vs, rows_vs))
        return pools_k, pools_v, pools_ks, pools_vs

    return jax.jit(imp, donate_argnums=(0, 1, 2, 3))


class DecodeEngine:
    """Fixed-slot continuous-batching decode engine over a paged pool.

    Knobs (docs/GUIDE.md "Continuous-batching serving engine"):
    - `slots`: concurrent requests decoding per step; the step batch.
    - `page_size`: tokens per KV page (>= 16 to keep the Pallas kernel
      eligible; 64 default balances fragmentation vs table size).
    - `page_budget`: total KV positions in the pool across all slots
      (+1 null page is added internally). Defaults to the full
      reservation slots * max_context — set it lower to oversubscribe
      HBM against observed context lengths; admission then blocks on
      free pages, never preempts.
    - `max_context`: per-slot prompt + generation cap; fixes the page
      table width (static for the step trace).
    - `max_queue`: admission queue depth; submit() past it raises
      QueueFull (the HTTP layer's 503).
    - `step_horizon`: decode steps per host round-trip (one jitted
      scan) — amortizes dispatch latency at the price of quantizing
      admission/retirement latency; clamped per call to the nearest
      slot completion so no budget is overrun mid-scan.
    - `prefill_chunk_tokens`: per-round prompt-token budget of chunked
      admission (the mixed prefill+decode step). While any slot is
      admitting, each round prefills at most this many tokens of the
      OLDEST admitting prompt and advances every other live slot by one
      decode token in the same jitted dispatch — the decode-latency
      interference of a long prompt is bounded by one budget-sized
      chunk forward per token. 0 disables chunking: whole-prompt
      bucketed prefill at admission (the pre-ISSUE-4 behavior; wins for
      single-tenant short-prompt traffic, docs/GUIDE.md).
    - `warmup_compile`: pre-trace the mixed-step/decode-scan
      executables for the configured buckets at `start()` so the first
      request doesn't eat the compile stall (opt-in; warmup rounds run
      every slot idle, so they only scribble the dead null page).
    - `prefix_cache`: share prompt-prefix K/V pages across requests
      (inference/prefix_cache.py; page-aligned hash index, COW on
      mid-page divergence, refcounted free-list returns, LRU eviction
      under pool pressure). Requires chunked admission
      (prefill_chunk_tokens > 0): the suffix prefill must attend to
      pooled context. Requests with return_log_probs bypass matching
      (their PROMPT logprobs require the full forward) but still
      register their pages for others.
    - `spec_decode_k`: speculative decoding — a prompt-lookup n-gram
      drafter proposes up to k tokens per greedy slot per round,
      verified in one width-(k+1) ragged chunk (ONE executable per
      greedy specialization). Greedy token streams stay bitwise;
      sampled slots ride the same round as plain decode rows. 0
      disables.
    - `kv_dtype` ("bf16" default | "int8", ISSUE 9): page-pool storage
      dtype. int8 stores K/V as int8 with per-(token, group) fp32
      scale pools (quantized at write time in the scatter paths,
      dequantized in-register by the paged kernels / on the gathered
      view by the XLA twins) — roughly half the pool bytes/token and
      half the decode kernels' cache traffic, at a measured (bench
      `extra.quant`) greedy logprob drift. bf16 keeps the bitwise
      generate_tokens parity contract.
    - `quantize_weights` (default False): one-shot weight-only int8 of
      the decode GEMV weights (per-output-channel scales,
      prepare_decode_params(quantize_int8=True)); decode matvecs read
      half the weight bytes. Decode-only — the fp tree is untouched.
    - `serving_tp` (default 1, ISSUE 14): tensor-parallel degree of
      the serving mesh. The K/V page pools (and int8 scale pools)
      shard over the head/group axis (parallel/sharding.kv_pool_spec
      — the zero1_axis one-rule idiom), decode params shard by
      decode_param_specs, and every jitted step runs under pjit on a
      (1,1,1,tp) mesh via GSPMD constraints (shard_map's
      partial-manual form cannot lower on this XLA build,
      KNOWN_FAILURES.md). Page tables / lengths / per-slot sampling
      arrays stay replicated host-trivial operands. Must divide
      num_query_groups. Greedy TOKEN streams match the single-chip
      engine bitwise; logprobs carry the same last-ulps latitude the
      backend's matmul blocking already has across chunk widths (the
      tp all-reduce reorders the row-parallel reduction) — pinned in
      tests/test_tp_serving.py. Incompatible with quantize_weights
      (flattened-GLU layout); docs/GUIDE.md "Serving on a tp mesh &
      replica routing".
    - `devices` (default None = jax.devices() prefix): pin the engine
      to a device subset — N emulated replicas on one host each own a
      device (inference/router.py, bench scaleout).
    - `replica_id` (default None): tag this engine as replica i behind
      a router: counters() grows `serve_replica_id`, flight-recorder
      events and trace spans carry `replica`, and the SSE `id:` field
      becomes "i-rid", so N replicas' aggregated metrics and dumps
      stay distinguishable. None keeps every schema byte-compatible
      with the standalone engine.
    - `trace_dir` (ISSUE 13): enable the host span tracer; the Chrome
      trace-event JSON exports here at stop(). `record_dir`: where the
      flight recorder dumps its crash artifact (defaults to trace_dir;
      None = in-memory + log-summary only). `flight_recorder_size`:
      the event ring bound. Telemetry never touches jitted code —
      telemetry-on steps are bitwise telemetry-off
      (docs/GUIDE.md "Observability").
    - `cost_registry` (default False, ISSUE 15): capture each minted
      executable's compiled cost (cost_analysis FLOPs/bytes +
      memory_analysis temp/args bytes) at MINT time into a
      telemetry/costs.CostRegistry — never in the per-round path.
      Unlocks the per-request device-cost record stamped into retire
      events (prefill/decode/spec-accepted tokens, page-rounds held,
      modeled FLOPs), the `serve_modeled_gflops`/`serve_page_rounds`
      aggregates, and (with a known chip) the
      `serve_dispatch_overhead_pct` gauge — modeled roofline device
      time vs measured round wall. Opt-in because capture pays one
      extra AOT compile per minted executable (docs/GUIDE.md "Goodput
      & device-cost accounting"); all gauges it adds are absent when
      off, keeping the /metrics JSON byte-compatible.
    - `chip_spec` (default None = detect from the engine's devices):
      chipspec table override ("v5e"/"v5p"/"v4") for the roofline
      denominators — the only way to get deterministic overhead
      gauges on the CPU harness.
    - `perf_sentinel_ksigma` (default 0.0 = off, ISSUE 15): arm the
      perf-regression sentinel on the DECODE-SCAN per-token-advance
      round latency — the one homogeneous series. Mixed rounds are
      excluded (their wall carries a prefill chunk: long-prompt
      admission would read as a false regression) and so are spec
      rounds (their per-advance moves with the ACCEPT RATE: a prompt
      mix dropping acceptance is not a hardware regression);
      interference and acceptance stay the serve_decode_round_ms
      histogram's and serve_spec_accept_rate's jobs. `patience`
      consecutive rounds above median + ksigma * 1.4826*MAD of the
      recent window trips it — flight-recorder event trail, a
      `serve_perf_regressions` counter, and an auto-dump of the ring
      into record_dir through the same postmortem path as poison.
      `perf_sentinel_window`/`perf_sentinel_patience` tune it
      (docs/GUIDE.md sentinel tuning table).

    Pages are reserved UP FRONT at admission for the request's whole
    prompt + tokens_to_generate reach, so a running request can never
    be starved mid-flight (no preemption path to get wrong); the
    trade is documented in the guide.
    """

    def __init__(self, model, params, *, slots: int = 4,
                 page_size: int = 64, max_context: int = 1024,
                 page_budget: Optional[int] = None, max_queue: int = 64,
                 step_horizon: int = 8,
                 prefill_chunk_tokens: int = 256,
                 warmup_compile: bool = False,
                 prefix_cache: bool = False,
                 spec_decode_k: int = 0,
                 window_reclaim: bool = True,
                 kv_dtype: str = "bf16",
                 quantize_weights: bool = False,
                 serving_tp: int = 1,
                 devices=None,
                 replica_id: Optional[int] = None,
                 termination_id: Optional[int] = None,
                 vocab_size: Optional[int] = None, timers=None,
                 trace_dir: Optional[str] = None,
                 record_dir: Optional[str] = None,
                 flight_recorder_size: int = 4096,
                 cost_registry: bool = False,
                 chip_spec: Optional[str] = None,
                 perf_sentinel_ksigma: float = 0.0,
                 perf_sentinel_window: int = 64,
                 perf_sentinel_patience: int = 8):
        assert max_context % page_size == 0, \
            "max_context must be a multiple of page_size"
        if kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be 'bf16' (the model compute dtype — "
                f"the bitwise-parity default) or 'int8' (quantized "
                f"pages, docs/GUIDE.md 'Quantized serving'), got "
                f"{kv_dtype!r}")
        self.model = model
        self.cfg = model.cfg
        # -- tp mesh (ISSUE 14) -------------------------------------------
        # serving_tp > 1: the pools shard over the head/group axis
        # (kv_pool_spec, the zero1_axis one-rule idiom) and every
        # jitted step runs under pjit on a (1, 1, 1, tp) mesh via GSPMD
        # constraints — NOT shard_map, whose partial-manual form this
        # XLA build cannot lower (KNOWN_FAILURES.md). `devices` pins
        # the engine to a device subset even at tp=1 (N emulated
        # replicas on one host each own a device — bench scaleout /
        # inference/router.py). Page tables, lengths, and the per-slot
        # sampling arrays stay REPLICATED: they are host-trivial
        # scalar-prefetch operands every chip must agree on.
        self.serving_tp = max(1, serving_tp)
        self.replica_id = replica_id
        if self.serving_tp > 1 or devices is not None:
            from megatron_llm_tpu.parallel.mesh import (
                ParallelContext,
                build_mesh,
            )

            if self.cfg.num_query_groups % self.serving_tp != 0:
                raise ValueError(
                    f"serving_tp={self.serving_tp} must divide the KV "
                    f"group count ({self.cfg.num_query_groups}): the "
                    f"page pools shard over the group axis "
                    f"(parallel/sharding.kv_pool_spec) — use a tp that "
                    f"divides num_query_groups, or replicate the "
                    f"engine behind the router instead (docs/GUIDE.md "
                    f"'Serving on a tp mesh & replica routing')")
            if quantize_weights and self.serving_tp > 1:
                raise ValueError(
                    "quantize_weights is single-chip-layout only (the "
                    "weight-only int8 decode tree bakes the flattened "
                    "(h, 2f) GLU view, whose gate|up concat crosses "
                    "the tp shard boundary); serve the fp decode tree "
                    "on a tp mesh, or quantize at tp=1 (docs/GUIDE.md "
                    "'Serving on a tp mesh & replica routing')")
            self._ctx = ParallelContext(
                build_mesh(tp=self.serving_tp, devices=devices))
            self._rep = self._ctx.sharding()  # replicated operands
        else:
            self._ctx = None
            self._rep = None
        self.slots = slots
        self.page_size = page_size
        self.max_pages_per_slot = max_context // page_size
        self.max_context = max_context
        if page_budget is None:
            page_budget = slots * max_context
        assert page_budget % page_size == 0
        self.num_pages = 1 + page_budget // page_size  # +1: null page 0
        self.max_queue = max_queue
        # decode steps per host round-trip: dispatch latency amortizer
        # (admission/retirement latency is quantized by it; the host
        # clamps each call to the nearest slot completion so no budget
        # is overrun, and buckets the clamp to powers of two so at most
        # log2(step_horizon)+1 scan lengths are ever traced)
        self.step_horizon = max(1, step_horizon)
        assert prefill_chunk_tokens >= 0
        if prefill_chunk_tokens > max_context:
            prefill_chunk_tokens = max_context
        self.prefill_chunk_tokens = prefill_chunk_tokens
        self.warmup_compile = warmup_compile
        if prefix_cache and not prefill_chunk_tokens:
            raise ValueError(
                "prefix_cache requires chunked admission "
                "(prefill_chunk_tokens > 0): a cache-hit suffix prefill "
                "must attend to pooled prefix K/V, which the whole-prompt "
                "dense prefill cannot — enable chunking or disable the "
                "prefix cache")
        self._prefix = PrefixCache(page_size) if prefix_cache else None
        assert spec_decode_k >= 0
        self.spec_decode_k = spec_decode_k
        # sliding-window serving (ISSUE 19): static per-model — every
        # serving trace of a window-enabled model bakes the O(window)
        # kernel clamp in, and the host reclaims pages wholly out of
        # every live window back to the free pool mid-flight (see
        # _reclaim_window_pages). Windowed slots also ALLOCATE lazily:
        # admission reserves only the window-bound page count and
        # _ensure_pages tops the frontier up just before each round
        # (see _window_slot_pages) — pool capacity prices O(window) per
        # long slot, not O(prompt + budget).
        w = getattr(self.cfg, "attention_window_size", None)
        self.window = int(w) if w else None
        # window_reclaim=False keeps the window MASK but never frees a
        # page mid-flight — the A/B control the bitwise reclamation pin
        # runs against (outputs must be identical by construction:
        # reclaimed pages are exactly the ones no kernel reads again)
        self.window_reclaim = bool(window_reclaim)
        if self.window is not None and not prefill_chunk_tokens:
            raise ValueError(
                "attention_window_size requires chunked admission "
                "(prefill_chunk_tokens > 0): whole-prompt admission "
                "prefills through the DENSE path, which carries no "
                "window mask, so its cache would disagree with every "
                "windowed chunked/decode step — enable chunking or "
                "clear the window")
        self._window_reclaimed = 0
        self.kv_dtype = kv_dtype
        self.quantize_weights = quantize_weights
        self.termination_id = termination_id
        self.vocab_size = vocab_size
        self.timers = timers

        if quantize_weights:
            if not hasattr(model, "prepare_decode_params"):
                raise ValueError(
                    "quantize_weights=True needs the model's "
                    "prepare_decode_params(quantize_int8=...) decode "
                    "layout (weight-only int8 is a decode-tree "
                    "transform)")
            dec = model.prepare_decode_params(params, quantize_int8=True)
        elif hasattr(model, "prepare_decode_params"):
            # tp engines keep the UNFLATTENED (h, 2, f) GLU layout: the
            # single-chip (h, 2f) flatten concatenates gate|up along
            # the axis tp shards (parallel/sharding.decode_param_specs)
            dec = model.prepare_decode_params(
                params, flatten_glu=(self.serving_tp == 1))
        else:
            dec = params
        if self._ctx is not None:
            if self.serving_tp > 1:
                from megatron_llm_tpu.parallel.sharding import (
                    decode_param_shardings,
                )

                dec = jax.device_put(
                    dec, decode_param_shardings(self._ctx, self.cfg, dec))
            else:
                # tp=1 on a pinned device (an emulated replica): the
                # whole tree rides the one-device mesh, replicated
                dec = jax.device_put(dec, self._rep)
        self._dec_params = dec
        caches = model.init_paged_kv_caches(
            slots, self.num_pages, page_size, self.max_pages_per_slot,
            kv_dtype=jnp.int8 if kv_dtype == "int8" else None,
            mesh_ctx=self._ctx)
        self._pools_k = caches["k_pages_layers"]
        self._pools_v = caches["v_pages_layers"]
        # int8 engines (ISSUE 9): per-layer fp32 scale pools ride every
        # jitted step alongside the data pools (donated, updated in
        # place); fp engines carry empty tuples through the same
        # signatures — ONE step-fn shape for both modes
        self._pools_ks = caches.get("k_scales_layers", ())
        self._pools_vs = caches.get("v_scales_layers", ())
        if kv_dtype == "int8" and page_size % 32 != 0:
            # the int8 Pallas gate needs 32-sublane pages: with this
            # page_size every TPU step silently takes the dequantizing
            # XLA twin (full fp32 pool materialization per layer per
            # step) — worse bandwidth than the bf16 path the operator
            # opted out of. Legitimate off-TPU (the twin IS the CPU
            # path), so warn loudly instead of refusing.
            _logger.warning(
                "kv_dtype=int8 with page_size=%d: the int8 paged "
                "kernels need page_size %% 32 == 0 — on TPU this "
                "config serves every step through the dequantizing "
                "XLA fallback and forfeits the bandwidth win. Use "
                "page_size 32/64 (docs/GUIDE.md 'Quantized serving')",
                page_size)
        V = self.cfg.padded_vocab_size
        self._last_logits = self._dev(np.zeros((slots, V), np.float32))
        # host-authoritative mirrors (tiny; shipped to device each step)
        self._pt = np.zeros((slots, self.max_pages_per_slot), np.int32)
        self._lengths = np.zeros((slots,), np.int32)
        self._free_pages = list(range(self.num_pages - 1, 0, -1))

        self._slots = [_Slot() for _ in range(slots)]
        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._next_rid = 0
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._broken: Optional[str] = None

        # -- compiled-cost registry + perf sentinel (ISSUE 15) ------------
        # Construction precedes the _copy_fn mint below so the first
        # executable this engine ever mints is already capturable.
        self.costs = None
        self.chip = None
        if cost_registry:
            from megatron_llm_tpu.telemetry.chipspec import detect_chip
            from megatron_llm_tpu.telemetry.costs import CostRegistry

            self.chip = detect_chip(
                devices=self._ctx.mesh.devices.flatten().tolist()
                if self._ctx is not None else None,
                override=chip_spec)
            # owner=self: the mint-listener inventory tracks THIS
            # engine's variants, not a sibling replica's
            self.costs = CostRegistry(chip=self.chip, owner=self).attach()
        # analytic per-token decode-FLOPs coefficients for the
        # per-request cost record (telemetry/chipspec.py model):
        # linear term 2*N over the decode tree, attention term
        # 4*L*h per cached position
        self._cost_fpt_linear = 0.0
        self._cost_attn_coeff = 0.0
        if self.costs is not None:
            n_dec = sum(
                int(np.prod(l.shape)) for l in jax.tree.leaves(dec)
                if hasattr(l, "shape"))
            self._cost_fpt_linear = 2.0 * n_dec
            self._cost_attn_coeff = (4.0 * self.cfg.num_layers
                                     * self.cfg.hidden_size)
        # modeled-vs-measured dispatch accounting (round granularity)
        self._modeled_device_ms = 0.0
        self._measured_round_ms = 0.0
        self._modeled_gflops = 0.0
        self._page_rounds = 0
        self._sentinel = None
        if perf_sentinel_ksigma > 0:
            from megatron_llm_tpu.telemetry.sentinel import PerfSentinel

            self._sentinel = PerfSentinel(
                k_sigma=perf_sentinel_ksigma,
                # clamped like the trainer's (arguments.py path): a
                # too-small CLI value degrades to the floor instead of
                # an unexplained AssertionError at server startup
                window=max(perf_sentinel_window, 4),
                patience=max(perf_sentinel_patience, 1),
                recorder=None,  # wired to self.recorder below (the
                # recorder is constructed in the telemetry block)
                name="decode_round_ms")

        self._step_fns: dict = {}  # horizon bucket -> jitted scan
        self._mixed_fns: dict = {}  # (width bucket, greedy) -> jitted
        # spec verification executables: ONE width (spec_decode_k + 1)
        # per greedy specialization — shorter drafts pad via chunk_lens,
        # so traffic can never mint per-draft-length buckets
        # (tests/test_spec_decode.py pins the count)
        self._spec_fns: dict = {}  # (width, greedy) -> jitted
        self._copy_fn = _make_page_copy_fn(
            contract_key=(), contract_owner=self, contract_budget=1)
        self._capture_cost("engine.page_copy", (), self._copy_fn,
                           self._null_copy_args)
        # cross-replica KV hand-off pair (ISSUE 17). Minted eagerly
        # (jax.jit is lazy — no trace happens until a transfer or the
        # audit calls them) so the contract inventory and the audit's
        # entry-point walk see the same surface on every engine.
        self._export_fn = _make_page_export_fn(
            contract_key=(), contract_owner=self, contract_budget=1)
        self._capture_cost("engine.page_export", (), self._export_fn,
                           self._null_export_args)
        self._import_fn = _make_page_import_fn(
            contract_key=(), contract_owner=self, contract_budget=1)
        self._capture_cost("engine.page_import", (), self._import_fn,
                           self._null_import_args)
        # transfer inbox: export/import ops funneled onto the serve
        # thread. The serve loop DONATES the page pools every round, so
        # a router-thread jit on self._pools_* would race a deleted
        # buffer; and the PrefixCache's documented thread contract puts
        # every mutating call on the serve thread. _step_inner drains
        # this deque at the top of each round; with no serve thread
        # (manual-step tests, bench setup) the op is applied inline.
        self._xfers: collections.deque = collections.deque()
        # hand-off accounting (gated: exported via counters() only
        # when a transfer has happened, keeping legacy JSON byte-
        # compatible per the PR 15 pin)
        self._transfers_out = 0
        self._transfer_pages_out = 0
        self._transfers_in = 0
        self._transfer_pages_in = 0
        # whole-prompt prefill executables, LRU-bounded like the pp
        # decode cache (api.py _pp_decode_fn): prompt buckets are an
        # unbounded key space across traffic
        self._prefill_fns: "collections.OrderedDict" = \
            collections.OrderedDict()

        # counters (exported through the timers-gauge path)
        self._admitted = 0
        self._retired = 0
        self._timed_out = 0  # deadline_s expiries (queued + running)
        self._steps = 0
        self._tokens_out = 0
        self._prefill_tokens = 0
        self._cancelled = 0  # cancel() reaps (disconnected streams)
        # speculative decoding accounting: proposed vs accepted draft
        # tokens (the acceptance-rate gauge) and spec rounds run
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._t0 = time.perf_counter()
        # recent-window latency gauges: submit -> first generated token
        # per request, and wall ms per decode-token advance per round
        # (a mixed round IS one decode step — its latency is exactly the
        # chunked-prefill interference the p95 gauge exists to expose)
        self._ttft_ms: collections.deque = collections.deque(maxlen=256)
        self._decode_ms: collections.deque = collections.deque(maxlen=256)
        # per-round accounting (prefill/decode token split + wall ms),
        # the auditable budget trail (tests pin the interference bound
        # on it; bench reads it for the decode-p95 row)
        self._round_log: collections.deque = collections.deque(
            maxlen=4096)

        # -- telemetry (ISSUE 13) -----------------------------------------
        # Span tracer: enabled only with a trace_dir (the off path is
        # one attribute check per emit site); exported as Chrome trace
        # JSON at stop(). Flight recorder: ALWAYS on — a bounded ring
        # of per-round/lifecycle events auto-dumped on serve-loop
        # poison (record_dir; falls back to trace_dir) and served on
        # demand at GET /flight_record. Histograms: the distributional
        # SLO metrics behind the Prometheus text exposition on
        # GET /metrics. NONE of this touches jitted code: telemetry-on
        # steps are bitwise telemetry-off (tests/test_telemetry.py +
        # the graft-check audit pin it).
        self.trace_dir = trace_dir
        self.record_dir = record_dir if record_dir is not None else trace_dir
        self.tracer: SpanTracer = (SpanTracer(enabled=True)
                                   if trace_dir else NULL_TRACER)
        if replica_id is not None:
            # replica correlation (ISSUE 14): every span and flight-
            # recorder event from this engine names its replica, so
            # aggregated dumps from N replicas behind the router stay
            # attributable (the SSE `id:` field and counters() carry
            # the same tag)
            self.tracer.set_context(replica=replica_id)
        self.recorder = FlightRecorder(
            flight_recorder_size,
            base=None if replica_id is None else {"replica": replica_id})
        if self._sentinel is not None:
            # the sentinel's bad/trip event trail lands in the same
            # flight ring its trip auto-dumps (ISSUE 15)
            self._sentinel.recorder = self.recorder
        self._hists = {
            "serve_ttft_ms": Histogram(
                "serve_ttft_ms", help_text="submit -> first generated "
                "token, per request"),
            "serve_decode_round_ms": Histogram(
                "serve_decode_round_ms", help_text="wall ms per decode-"
                "token advance per round (mixed rounds included: the "
                "chunked-prefill interference distribution)"),
            "serve_queue_wait_ms": Histogram(
                "serve_queue_wait_ms", help_text="submit -> slot "
                "admission, per request"),
        }
        self._rounds = 0  # did-work scheduler rounds (telemetry clock)
        # fault-injection hook (ISSUE 20, inference/chaos.py): called at
        # the top of every scheduler round INSIDE the round's timed
        # window, so an injected stall rides the round wall the perf
        # sentinel measures (an honest trip, not a synthetic counter
        # bump) and an injected raise kills the serve loop through the
        # REAL poison path (flight-ring dump + _fail_all + _broken).
        # None (the default) is one attribute check per round — the
        # chaos-off hot path is unchanged.
        self._fault_hook = None
        # jax.profiler capture hook (POST /profile): armed request ->
        # started before the next round, stopped after N did-work
        # rounds; start/stop failures are LOGGED no-ops (capture is a
        # diagnostic, never a crash source)
        self._profile_pending: Optional[tuple] = None
        self._profile_active = False
        self._profile_left = 0
        self._profile_dir: Optional[str] = None

    # -- tp-mesh plumbing (ISSUE 14) ---------------------------------------

    def _dev(self, x, dtype=None):
        """Host operand -> device array. Single-chip engines keep the
        jnp.asarray fast path (bitwise-unchanged); mesh engines
        device_put REPLICATED onto the serving mesh — a committed
        single-device array mixed into a pjit over sharded pools would
        be an incompatible-devices error, and every small operand
        (page table, lengths, sampling knob arrays, scan inputs) is by
        contract replicated (host-trivial scalar prefetch)."""
        if dtype is not None:
            x = np.asarray(x, dtype)
        if self._ctx is None:
            return jnp.asarray(x)
        return jax.device_put(np.asarray(x), self._rep)

    def _capture_cost(self, name: str, key, fn, args_thunk) -> None:
        """Compiled-cost capture for one freshly MINTED executable
        (ISSUE 15): lowers `fn` against warmup-style example args (the
        thunk defers building them — and any device_put they need —
        until the registry is actually on) and records cost_analysis
        FLOPs/bytes + memory_analysis temp/args under (contract, key).
        Mint-time only by construction: every call site sits next to a
        builder invocation, never in the per-round path (the GR006
        contract); the capture itself pays one extra AOT compile per
        executable, which is why cost_registry is opt-in."""
        if self.costs is None:
            return
        with self.mesh_scope():
            self.costs.capture(name, key, fn, args_thunk())

    def _artifact_tag(self, base: str) -> str:
        """Filename tag for exported artifacts (span traces, flight-
        record dumps): N in-process replicas share a pid, so an
        untagged per-pid filename would let later replicas silently
        overwrite earlier ones' postmortems — the replica id joins the
        name whenever one is set."""
        if self.replica_id is None:
            return base
        return f"{base}-r{self.replica_id}"

    def mesh_scope(self):
        """Context manager installing the serving-mesh ParallelContext
        for the duration of a dispatch: the model's shard_activation
        constraints read the global context AT TRACE TIME, so every
        site that can trace a step executable (step()/warmup()/
        audit_entry_points()) runs under this scope. GSPMD then
        partitions the traced program over the tp mesh — pools sharded
        per kv_pool_spec, activations steered by the existing
        heads/groups/ffn constraint sites, collectives materialised by
        the partitioner (the pjit-TPUv4 playbook; shard_map is
        unusable here, KNOWN_FAILURES.md). `use_mesh` installs a
        THREAD-LOCAL override (parallel/mesh.py), so N tp engines'
        serve threads each trace under their own mesh concurrently —
        no process-wide lock, no fleet serialization. tp=1 engines
        (including device-pinned replicas) return a null scope: a
        1-device mesh needs no constraints at all."""
        if self._ctx is None or self.serving_tp == 1:
            return contextlib.nullcontext()
        from megatron_llm_tpu.parallel.mesh import use_mesh

        return use_mesh(self._ctx)

    # -- admission ---------------------------------------------------------

    def submit(self, prompt: List[int], tokens_to_generate: int, *,
               top_k: int = 1, top_p: float = 0.0,
               temperature: float = 1.0, seed: int = 0,
               return_log_probs: bool = False,
               use_eod_for_early_termination: bool = True,
               deadline_s: Optional[float] = None,
               stream: bool = False,
               ) -> EngineRequest:
        """Queue one request. Raises ValueError when it cannot ever fit
        (prompt + generation past max_context) and QueueFull when the
        queue is at capacity — callers map the latter to 503.

        `deadline_s` is a wall-clock budget measured from submit: once
        exceeded, the request's waiter fails with TimeoutError and —
        when it was running — its slot retires and the pages return to
        the free list, so an abandoned request can never pin pool
        capacity or wedge the FIFO head forever.

        `stream=True` attaches a per-request token queue
        (`req.stream_q`): every generated token id is pushed as it is
        booked, and a None sentinel closes the stream on completion OR
        failure — consumers must treat the sentinel, not result(), as
        end-of-stream, then call result() for the final status."""
        total = len(prompt) + tokens_to_generate
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if tokens_to_generate < 1:
            raise ValueError("tokens_to_generate must be >= 1 (score-only "
                             "requests take the whole-batch path)")
        if total > self.max_context:
            raise ValueError(
                f"prompt ({len(prompt)}) + tokens_to_generate "
                f"({tokens_to_generate}) exceeds the engine max_context "
                f"({self.max_context})")
        # must also fit the POOL: under an oversubscribed page_budget a
        # request can satisfy max_context yet need more pages than the
        # pool holds — admitted, it would sit at the FIFO head forever
        # and starve everything behind it. Window-enabled engines
        # (ISSUE 19) price a request at the WINDOW bound, not its full
        # reach: out-of-window pages reclaim mid-flight, so a long slot
        # can never hold more than _window_slot_pages at once.
        need = -(-total // self.page_size)
        if self.window is not None and self.window_reclaim:
            need = min(need, self._window_slot_pages())
        if need > self.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages but the pool holds only "
                f"{self.num_pages - 1} (page_budget "
                f"{(self.num_pages - 1) * self.page_size} tokens); raise "
                f"page_budget or shrink the request")
        if self._broken is not None:
            raise RuntimeError(f"engine is stopped: {self._broken}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        req = EngineRequest(
            rid=-1, prompt=list(prompt),
            tokens_to_generate=tokens_to_generate,
            replica_id=self.replica_id,
            greedy=(top_k == 1), top_k=top_k, top_p=top_p,
            temperature=temperature, seed=seed,
            return_log_probs=return_log_probs,
            use_eod_for_early_termination=use_eod_for_early_termination,
            deadline_s=deadline_s,
            stream_q=queue_mod.SimpleQueue() if stream else None,
        )
        req.t_submit = time.perf_counter()
        with self._lock:
            if len(self._queue) >= self.max_queue:
                raise QueueFull(
                    f"engine queue at capacity ({self.max_queue})")
            req.rid = self._next_rid
            self._next_rid += 1
            self._queue.append(req)
            self._work.notify()
        # per-request ID assigned above is THE correlation key: it rides
        # SSE `id:` fields, server error logs, trace spans and these
        # flight-recorder events (ISSUE 13)
        self.recorder.record(
            "submit", rid=req.rid, prompt_tokens=len(req.prompt),
            tokens_to_generate=tokens_to_generate, stream=stream)
        return req

    @staticmethod
    def _finish(req: EngineRequest):
        """The ONE completion point: wake the waiter and close the
        token stream (None sentinel) so an SSE consumer can never hang
        on a request that already failed/retired."""
        req.done.set()
        if req.stream_q is not None:
            req.stream_q.put(None)

    def cancel(self, req: EngineRequest):
        """Abandon a request (e.g. its streaming client disconnected):
        queued requests fail their waiter immediately; a running one is
        flagged and reaped by the scheduler's next round — the slot
        retires and its pages return/release exactly like a normal
        retirement, so shared-prefix refcounts stay intact. Idempotent;
        a no-op on requests that already finished."""
        with self._lock:
            if req.done.is_set():
                return
            req.cancelled = True
            try:
                self._queue.remove(req)
            except ValueError:
                # not queued: running (the serve loop reaps it) or
                # being admitted right now (ditto, next round)
                self._work.notify()
                return
            # inside the lock: the serve thread increments this counter
            # too (running-slot reap), and a racing unlocked += would
            # drop counts under concurrent disconnects
            self._cancelled += 1
        req.error = f"request {req.rid} cancelled"
        self._finish(req)

    _PREFILL_CACHE_CAP = 8

    def _prefill_fn(self, plen):
        """Whole-prompt prefill executable per bucket, LRU-bounded at
        _PREFILL_CACHE_CAP (requeue-on-hit, loud eviction) — the same
        contract as the pp decode cache (api.py _pp_decode_fn): prompt
        buckets are a small-but-unbounded key space across traffic, and
        an unbounded dict held every executable forever."""
        if plen in self._prefill_fns:
            fn = self._prefill_fns.pop(plen)
            self._prefill_fns[plen] = fn  # LRU requeue
            return fn
        while len(self._prefill_fns) >= self._PREFILL_CACHE_CAP:
            old, _ = self._prefill_fns.popitem(last=False)
            # the budget counts LIVE executables: eviction un-counts
            release_variant("engine.prefill_bucket", old, owner=self)
            _logger.warning(
                "prefill executable cache full (%d): evicting LRU bucket "
                "%d; the next prompt at that bucket recompiles its "
                "prefill (chunked admission — prefill_chunk_tokens > 0 — "
                "avoids per-prompt buckets entirely)",
                self._PREFILL_CACHE_CAP, old,
            )
        fn = _make_prefill_fn(self.model, plen, self.page_size,
                              contract_key=plen, contract_owner=self,
                              contract_budget=self._PREFILL_CACHE_CAP)
        self._prefill_fns[plen] = fn
        self._capture_cost("engine.prefill_bucket", plen, fn,
                           lambda: self._null_prefill_args(plen))
        return fn

    def _admit(self) -> int:
        """Move queued requests into free slots while pages allow.
        FIFO head-of-line: a request that does not fit blocks the ones
        behind it (predictable latency ordering, no starvation).
        Returns the prompt tokens PREFILLED ON DEVICE during this call
        (whole-prompt mode only; chunked admission does no device work
        here), so the caller's round accounting can attribute the
        in-round prefill stall honestly."""
        prefilled = 0
        for si, slot in enumerate(self._slots):
            if slot.req is not None:
                continue
            with self._lock:
                if not self._queue:
                    return prefilled
                req = self._queue[0]
                need = -(-(len(req.prompt) + req.tokens_to_generate)
                         // self.page_size)
                # prefix sharing: cache-hit pages map into the page
                # table instead of being allocated + prefilled.
                # return_log_probs requests bypass MATCHING (their
                # prompt logprobs need the full forward) but still
                # register their pages below for later requests.
                match = None
                if self._prefix is not None and not req.return_log_probs:
                    match = self._prefix.lookup(req.prompt)
                    if match.matched == 0:
                        match = None
                matched_pages = match.full_pages if match else 0
                # windowed engines (ISSUE 19) reserve only the window
                # bound up front — _ensure_pages tops the frontier up
                # before each round and _reclaim_window_pages returns
                # dead pages, so a long request never holds O(prompt +
                # budget) pages. Shared prefix pages are refcounts, not
                # allocations, so a hit larger than the bound still
                # maps whole (its out-of-window pages release back to
                # the cache on the first reclaim pass); a COW divergence
                # always gets its fresh private page.
                cap = need
                if self.window is not None and self.window_reclaim:
                    cap = max(min(need, self._window_slot_pages()),
                              matched_pages
                              + (1 if match is not None
                                 and match.cow_src is not None else 0))
                need_new = max(cap - matched_pages, 0)
                if match is not None:
                    # pin the hit (incl. the COW source) BEFORE any
                    # eviction below could free it out from under us
                    self._prefix.acquire(match)
                if len(self._free_pages) < need_new \
                        and self._prefix is not None:
                    # reclaim unreferenced cached prefixes (LRU) before
                    # blocking the FIFO head on pages
                    evicted = self._prefix.evict(
                        need_new - len(self._free_pages))
                    if evicted:
                        self.tracer.instant("prefix_evict", rid=req.rid,
                                            pages=len(evicted))
                        self.recorder.record("prefix_evict", rid=req.rid,
                                             pages=len(evicted))
                    self._free_pages.extend(evicted)
                if len(self._free_pages) < need_new:
                    if match is not None:
                        self._prefix.unacquire(match)
                    return prefilled
                self._queue.popleft()
                # claim the slot INSIDE the lock: stop(drain=True) polls
                # "queue empty and no slot busy" — a request must never
                # be invisible to that check between dequeue and prefill
                slot.req = req
            fresh = [self._free_pages.pop() for _ in range(need_new)]
            pages = (list(match.pages) if match is not None else []) + fresh
            self._pt[si] = 0
            self._pt[si, :len(pages)] = pages
            slot.pages = pages
            slot.mapped = len(pages)
            slot.reclaimed = 0
            slot.generated = 0
            slot.sample_step = 0
            slot.registered = match.full_pages if match is not None else 0
            slot.bigram = {}
            slot.bigram_next = 0
            # per-request cost accounting (ISSUE 15): admission round,
            # prefill origin, and counters the retire record reads
            slot.admit_round = self._rounds
            slot.prefill_start = 0
            slot.prefilled = 0
            slot.spec_accepted = 0
            req.tokens = list(req.prompt)
            if self.prefill_chunk_tokens:
                # chunked admission: no device work here beyond the COW
                # copy — the prompt suffix prefills incrementally
                # through the mixed rounds, resumable at
                # slot.prefill_pos (== the matched-token count: cache-
                # hit positions never prefill)
                matched = 0
                if match is not None:
                    matched = match.matched
                    if match.cow_src is not None:
                        # copy-on-write: the divergent page starts as a
                        # private replica of the shared page (data AND
                        # scale pools — a quantized page is the pair);
                        # prefill resumes at the divergence offset
                        # inside it, so the shared page never sees this
                        # request's writes
                        with self.tracer.span(
                                "cow_copy", rid=req.rid,
                                src=match.cow_src,
                                dst=pages[match.full_pages]):
                            (self._pools_k, self._pools_v, self._pools_ks,
                             self._pools_vs) = self._copy_fn(
                                self._pools_k, self._pools_v,
                                self._pools_ks, self._pools_vs,
                                self._dev(match.cow_src, np.int32),
                                self._dev(pages[match.full_pages],
                                          np.int32))
                        self._prefix.release_page(match.cow_src)
                        self._prefix.cow_copies += 1
                if self._prefix is not None:
                    self._prefix.note(len(req.prompt), matched)
                slot.prefill_pos = matched
                slot.prefill_start = matched
                slot.forced = collections.deque()
                self._lengths[si] = matched
            else:
                plen = bucket_prefill_len(len(req.prompt))
                with self.tracer.span("prefill_bucket", rid=req.rid,
                                      slot=si, tokens=plen):
                    (self._pools_k, self._pools_v, self._pools_ks,
                     self._pools_vs, row_logits, plp) = \
                        self._prefill_fn(plen)(
                            self._dec_params, self._pools_k, self._pools_v,
                            self._pools_ks, self._pools_vs,
                            self._dev(np.asarray(req.prompt[:plen],
                                                 np.int32)[None]),
                            self._dev(self._pt[si]),
                        )
                self._last_logits = \
                    self._last_logits.at[si].set(row_logits)
                self._lengths[si] = plen
                slot.prefill_pos = len(req.prompt)
                slot.forced = collections.deque(req.prompt[plen:])
                slot.prefilled = plen
                self._prefill_tokens += plen
                prefilled += plen
                if req.return_log_probs:
                    req.log_probs = [float(x) for x in np.asarray(plp)]
            req.t_admit = time.perf_counter()
            # queue-wait telemetry: a retroactive span from the
            # request's own stamps (submit -> admission), plus the
            # histogram behind the Prometheus exposition
            wait_ms = (req.t_admit - req.t_submit) * 1e3
            self.tracer.complete("queue_wait", req.t_submit, req.t_admit,
                                 rid=req.rid, slot=si)
            self._hists["serve_queue_wait_ms"].observe(wait_ms)
            self.recorder.record(
                "admit", rid=req.rid, slot=si,
                queue_wait_ms=round(wait_ms, 3),
                prefill_start=slot.prefill_pos, pages=need)
            self._admitted += 1
        return prefilled

    def _request_cost(self, si: int) -> Optional[dict]:
        """The per-request device-cost record stamped into the retire
        event (ISSUE 15; cost_registry on). GR006 HOT_PATHS: pure host
        arithmetic over the slot's own counters and the host-side
        length mirror — never a device value. modeled_mflops is the
        analytic decode model (telemetry/chipspec.decode_flops_per_token
        coefficients precomputed at construction): the linear term over
        every position this request computed past its cache-hit offset,
        plus the attention integral over its context growth. A MODELED
        number by contract — it prices the request for cost-per-token
        attribution (the Gemma fine-tune-and-serve framing), it is not
        a profiler measurement."""
        if self.costs is None:
            return None
        slot = self._slots[si]
        req = slot.req
        final_len = int(self._lengths[si])
        start = slot.prefill_start
        computed = max(final_len - start, 0)
        rounds_held = self._rounds - slot.admit_round + 1
        pages = len(slot.pages)
        modeled = (self._cost_fpt_linear * computed
                   + 0.5 * self._cost_attn_coeff
                   * (final_len * final_len - start * start))
        return {
            "prompt_tokens": len(req.prompt),
            "cached_tokens": start,
            "prefill_tokens": slot.prefilled,
            "decode_tokens": slot.generated,
            "spec_accepted": slot.spec_accepted,
            "rounds_held": rounds_held,
            "pages": pages,
            "page_rounds": pages * rounds_held,
            "modeled_mflops": round(modeled / 1e6, 3),
        }

    def _retire(self, si: int):
        slot = self._slots[si]
        # cost record FIRST: it reads pages/lengths/counters this
        # method is about to reset
        cost = self._request_cost(si)
        if cost is not None:
            self._modeled_gflops += cost["modeled_mflops"] / 1e3
            self._page_rounds += cost["page_rounds"]
        if self._prefix is None:
            self._free_pages.extend(slot.pages)
        else:
            # refcounted returns: registered/shared pages stay with the
            # cache (evictable once unreferenced); only untracked pages
            # (generated tokens, partial prompt tails, lost insert
            # races) go straight back to the free list
            for pg in slot.pages:
                if not self._prefix.release(pg):
                    self._free_pages.append(pg)
        slot.pages = []
        slot.registered = 0
        slot.mapped = 0
        slot.reclaimed = 0
        self._pt[si] = 0
        self._lengths[si] = 0
        req = slot.req
        slot.req = None
        req.t_done = time.perf_counter()
        self._retired += 1
        self.tracer.instant("retire", rid=req.rid, slot=si,
                            generated=slot.generated,
                            error=req.error is not None)
        # the retire event schema grows the cost record ONLY when the
        # registry is on (the pre-ISSUE-15 event stays byte-identical)
        self.recorder.record("retire", rid=req.rid, slot=si,
                             generated=slot.generated, error=req.error,
                             **({"cost": cost} if cost is not None
                                else {}))
        self._finish(req)

    # -- sliding-window page bookkeeping (ISSUE 19) ------------------------

    def _window_slot_pages(self) -> int:
        """Peak physical pages a window-enabled slot holds: pages
        overlapping [L - window + 1, L + round_width) at any length L —
        the window itself, the widest span one round can write past it
        (decode horizon / prefill chunk / spec verify chunk), plus one
        boundary page each side. THE windowed capacity unit: submit()
        prices requests with it, _admit reserves it, start() logs it."""
        width = max(self.step_horizon, self.prefill_chunk_tokens,
                    self.spec_decode_k + 1)
        return min(self.max_pages_per_slot,
                   -(-(self.window + width) // self.page_size) + 1)

    def _ensure_pages(self, si: int, upto: int) -> None:
        """Top the slot's physical page frontier up to cover positions
        [0, upto): windowed slots allocate lazily (admission reserved
        only the window bound), so every round calls this for exactly
        the span it is about to write — the jitted step scatters K/V
        across page boundaries and must find real pages in the table.
        No-op when the frontier already covers `upto` (always, for
        non-window engines: admission mapped the full reach)."""
        if self.window is None:
            return
        want = min(-(-upto // self.page_size), self.max_pages_per_slot)
        s = self._slots[si]
        while s.mapped < want:
            if not self._free_pages and self._prefix is not None:
                self._free_pages.extend(
                    self._prefix.evict(want - s.mapped))
            if not self._free_pages:
                # unreachable when submit()/_admit price the window
                # bound correctly — reclamation returns a page for
                # every page the frontier consumes past the window
                raise RuntimeError(
                    f"page pool exhausted topping slot {si} up to "
                    f"{want} pages — window admission accounting bug")
            pg = self._free_pages.pop()
            self._pt[si, s.mapped] = pg
            s.pages.append(pg)
            s.mapped += 1

    def _reclaim_window_pages(self) -> None:
        """Release pages wholly below every live window back to the
        pool (the engine-side half of the ISSUE 19 tentpole). At length
        L the next query attends no position below L - window + 1, and
        lengths are monotone, so logical pages [0, (L+1-window) //
        page_size) are dead forever: the kernel's double-ended DMA
        clamp never dereferences their table entries again and the XLA
        twin masks their columns to exact-0 probabilities — freeing
        (and reusing) them is bitwise-invisible to the stream, which
        tests pin (reclamation ON == OFF). Refcount discipline:
        registered/shared prefix pages RELEASE to the cache (still
        evictable, never free-listed while referenced — a concurrent
        slot may be reading them inside ITS window); only private
        refcount-1 pages return to the free list. Table entries park
        on null page 0 and slot.reclaimed advances so _retire never
        double-releases; unregistered reclaimed pages also advance
        slot.registered so _register_prefix can never insert a freed
        page."""
        W = self.window
        if W is None or not self.window_reclaim:
            return
        ps = self.page_size
        for si, s in enumerate(self._slots):
            if s.req is None:
                continue
            dead = min(max(0, int(self._lengths[si]) + 1 - W) // ps,
                       s.mapped)
            if dead <= s.reclaimed:
                continue
            for p in range(s.reclaimed, dead):
                pg = int(self._pt[si, p])
                self._pt[si, p] = 0
                if s.pages and s.pages[0] == pg:
                    s.pages.pop(0)
                if pg == 0:
                    continue
                if self._prefix is not None and self._prefix.release(pg):
                    pass  # shared/registered: the cache retains it
                else:
                    self._free_pages.append(pg)
                self._window_reclaimed += 1
            n = dead - s.reclaimed
            s.reclaimed = dead
            if s.registered < dead:
                s.registered = dead
            self.tracer.instant("window_reclaim", rid=s.req.rid,
                                slot=si, pages=n)

    # -- the decode loop ---------------------------------------------------

    def _step_fn(self, horizon, all_greedy):
        key = (horizon, all_greedy)
        if key not in self._step_fns:
            # the contract registry is the ONE executable counter: a
            # horizon outside the pow2 bucket set blows the budget and
            # fails HERE, at mint time (analysis/contracts.py)
            self._step_fns[key] = _make_step_fn(
                self.model, self.vocab_size, horizon, all_greedy,
                contract_key=key, contract_owner=self,
                contract_budget=2 * len(horizon_buckets(self.step_horizon)))
            self._capture_cost(
                "engine.decode_scan", key, self._step_fns[key],
                lambda: self._null_scan_args(horizon))
        return self._step_fns[key]

    def _mixed_fn(self, width, all_greedy):
        key = (width, all_greedy)
        if key not in self._mixed_fns:
            self._mixed_fns[key] = _make_mixed_step_fn(
                self.model, self.vocab_size, width, all_greedy,
                contract_key=key, contract_owner=self,
                contract_budget=2 * len(
                    mixed_width_buckets(self.prefill_chunk_tokens)))
            self._capture_cost(
                "engine.mixed_step", key, self._mixed_fns[key],
                lambda: self._null_mixed_args(width))
        return self._mixed_fns[key]

    def _chunk_width(self, remaining: int) -> int:
        """Pow2 width bucket for a chunk covering `remaining` prompt
        tokens, capped at the budget: the mixed step traces once per
        distinct width, so at most log2(prefill_chunk_tokens)+1
        executables exist regardless of prompt lengths."""
        c = self.prefill_chunk_tokens
        if remaining >= c:
            return c
        return min(1 << (max(remaining, 1) - 1).bit_length(), c)

    def _book_token(self, i: int, tok: int, now: Optional[float] = None
                    ) -> bool:
        """Record one GENERATED token for slot i (TTFT on the first);
        retires the slot on eod/budget. Returns True if it retired."""
        s = self._slots[i]
        r = s.req
        r.tokens.append(tok)
        if r.stream_q is not None:
            r.stream_q.put(tok)
        s.generated += 1
        s.sample_step += 1
        self._tokens_out += 1
        if s.generated == 1:
            r.t_first = now if now is not None else time.perf_counter()
            ttft = (r.t_first - r.t_submit) * 1e3
            with self._lock:  # counters() sorts this window concurrently
                self._ttft_ms.append(ttft)
            self._hists["serve_ttft_ms"].observe(ttft)
            self.tracer.instant("first_token", rid=r.rid,
                                ttft_ms=round(ttft, 3))
        hit_eod = (r.use_eod_for_early_termination
                   and self.termination_id is not None
                   and tok == self.termination_id)
        if hit_eod or s.generated >= r.tokens_to_generate:
            self._retire(i)
            return True
        return False

    def _expire_deadlines(self) -> None:
        """Fail every queued/running request past its wall-clock
        deadline (TimeoutError at the waiter) and reclaim running slots'
        pages — run once per scheduler round, so enforcement granularity
        is one round (≤ one horizon scan / one mixed chunk)."""
        now = time.perf_counter()
        expired_q: List[EngineRequest] = []
        with self._lock:
            if any(r.expired(now) for r in self._queue):
                keep = collections.deque()
                for r in self._queue:
                    if r.expired(now):
                        expired_q.append(r)
                    else:
                        keep.append(r)
                self._queue = keep
        for r in expired_q:
            r.error = (f"request {r.rid} exceeded deadline_s="
                       f"{r.deadline_s} while queued")
            r.timed_out = True
            self._timed_out += 1
            self.recorder.record("timeout_queued", rid=r.rid,
                                 deadline_s=r.deadline_s)
            self._finish(r)
        for i, s in enumerate(self._slots):
            r = s.req
            if r is None:
                continue
            if r.cancelled:
                # cancel() mid-flight (e.g. streaming client gone):
                # retire exactly like a completion — pages return or
                # release through the refcounted path, shared-prefix
                # refcounts stay intact
                r.error = (f"request {r.rid} cancelled after "
                           f"{len(r.tokens) - len(r.prompt)}"
                           f"/{r.tokens_to_generate} generated tokens; "
                           f"slot retired, pages reclaimed")
                with self._lock:  # cancel() (HTTP thread) bumps it too
                    self._cancelled += 1
                self._retire(i)
                continue
            if r.expired(now):
                r.error = (f"request {r.rid} exceeded deadline_s="
                           f"{r.deadline_s} after {len(r.tokens) - len(r.prompt)}"
                           f"/{r.tokens_to_generate} generated tokens; "
                           f"slot retired, pages reclaimed")
                r.timed_out = True
                self._timed_out += 1
                self._retire(i)

    def step(self) -> bool:
        """One scheduler iteration (see _step_inner for the scheduling
        contract). This wrapper owns the telemetry clock (ISSUE 13):
        the jax.profiler capture hook (POST /profile) starts before /
        stops after the requested number of did-work rounds, the
        did-work round counter feeds span correlation, and every 256
        rounds the flight recorder takes a counters() snapshot. All of
        it is host bookkeeping — the jitted dispatches inside are
        telemetry-blind."""
        if self._profile_pending is not None:
            self._start_profile()
        with self.mesh_scope():
            # the serving-mesh context is read at TRACE time by the
            # model's shard_activation sites; any round can lazily
            # trace a new horizon/width bucket, so every dispatch runs
            # scoped (a no-op null scope on tp=1 engines)
            did = self._step_inner()
        if did:
            # out-of-window pages died as the round advanced lengths;
            # return them before the next round's admission/top-up
            # prices the pool (no-op for non-window engines)
            self._reclaim_window_pages()
            self._rounds += 1
            if self._rounds % 256 == 0:
                self.recorder.note_counters(self.counters())
        if self._profile_active:
            self._tick_profile(did)
        return did

    def request_profile(self, rounds: int,
                        trace_dir: Optional[str] = None) -> dict:
        """Arm a `jax.profiler` device capture of the next `rounds`
        did-work engine rounds (the POST /profile hook). The capture
        starts before the next round the serve loop runs and stops
        once `rounds` have completed; start/stop failures (no profiler
        on this runtime, a capture already running out-of-band) are
        LOGGED no-ops recorded in the flight ring — a diagnostic hook
        must never take the serve loop down. One capture at a time:
        a second request while one is armed/active is refused."""
        if rounds < 1:
            raise ValueError(f"rounds must be >= 1, got {rounds}")
        d = trace_dir or self.trace_dir or "./profile"
        with self._lock:
            if self._profile_active or self._profile_pending is not None:
                return {"ok": False,
                        "error": "a profiler capture is already in "
                                 "progress"}
            self._profile_pending = (int(rounds), d)
            self._work.notify()
        self.recorder.record("profile_armed", rounds=int(rounds), dir=d)
        return {"ok": True, "rounds": int(rounds), "trace_dir": d}

    def _start_profile(self) -> None:
        with self._lock:
            pending, self._profile_pending = self._profile_pending, None
            if pending is not None:
                # claim the one-capture slot BEFORE the unlocked
                # start_trace below: a request_profile racing in here
                # must see busy, not arm a second capture the profiler
                # will refuse
                rounds, d = pending
                self._profile_active = True
                self._profile_left = rounds
                self._profile_dir = d
        if pending is None:
            return
        try:
            jax.profiler.start_trace(d)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            with self._lock:
                self._profile_active = False
            _logger.warning(
                "jax.profiler capture unavailable (%r): the /profile "
                "request is a no-op on this runtime", e)
            self.recorder.record("profile_unsupported", error=repr(e))
            return
        self.recorder.record("profile_start", rounds=rounds, dir=d)

    def _tick_profile(self, did: bool) -> None:
        if did:
            self._profile_left -= 1
        if self._profile_left <= 0:
            self._stop_profile()

    def _stop_profile(self) -> None:
        if not self._profile_active:
            return
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            _logger.warning("jax.profiler stop_trace failed: %r", e)
        with self._lock:
            self._profile_active = False
        self.recorder.record("profile_done", dir=self._profile_dir)
        _logger.info("profiler capture complete: %s", self._profile_dir)

    def _step_inner(self) -> bool:
        """One scheduler iteration. Chunked admission (the default):
        while any slot is mid-prefill, run one MIXED round — a budget-
        bounded ragged chunk of the oldest admitting prompt plus one
        decode token for every other live slot, one jitted dispatch —
        otherwise one jitted scan of up to `step_horizon` decode steps.
        Each round's prefill/decode token split and wall time land in
        `_round_log` (the budget audit trail) and the decode-latency
        window behind `serve_decode_p95_ms`. Returns False when there
        was nothing to do (idle)."""
        t0 = time.perf_counter()
        if self._fault_hook is not None:
            self._fault_hook(self)
        self._expire_deadlines()
        did_xfer = self._apply_transfers()
        admitted_before = self._admitted
        t_adm = time.perf_counter()
        admit_prefilled = self._admit()
        if self._admitted != admitted_before:
            self.tracer.complete(
                "admit", t_adm, time.perf_counter(),
                admitted=self._admitted - admitted_before,
                prefilled_tokens=admit_prefilled)
        if self.prefill_chunk_tokens and any(
                s.prefilling for s in self._slots):
            dec_steps, pf_tokens, chunk_rid, mixed_key = \
                self._mixed_round()
            t1 = time.perf_counter()
            dt_ms = (t1 - t0) * 1e3
            with self._lock:  # counters() reads these windows concurrently
                self._round_log.append({
                    "prefill_tokens": pf_tokens, "decode_steps": 1,
                    "decode_slots": dec_steps, "ms": dt_ms})
                if dec_steps:
                    self._decode_ms.append(dt_ms)
            if dec_steps:
                self._hists["serve_decode_round_ms"].observe(dt_ms)
            # the sentinel deliberately does NOT eat mixed rounds:
            # their wall includes a prefill chunk, so a long-prompt
            # admission would look like `patience` consecutive
            # "regressions" against the per-token-advance baseline the
            # decode/spec rounds feed — interference is the
            # serve_decode_round_ms HISTOGRAM's job (bounded by
            # design), a sustained decode slowdown is the sentinel's
            self._note_dispatch("engine.mixed_step", mixed_key, dt_ms)
            # chunk-prefill span: rid-correlated — a streaming client's
            # stalled `id:` greps straight to these rounds
            self.tracer.complete(
                "round.mixed", t0, t1, round=self._rounds,
                rid=chunk_rid, prefill_tokens=pf_tokens,
                decode_slots=dec_steps)
            self.recorder.record(
                "round.mixed", round=self._rounds, rid=chunk_rid,
                prefill_tokens=pf_tokens, decode_slots=dec_steps,
                ms=round(dt_ms, 3))
            return True
        if self.spec_decode_k:
            drafts = self._collect_drafts()
            if drafts:
                self._spec_round(drafts, t0, admit_prefilled)
                return True
        return self._decode_round(t0, admit_prefilled) or did_xfer

    def _note_dispatch(self, name: str, key, dt_ms: float) -> None:
        """Round-granularity modeled-vs-measured accounting behind the
        serve_dispatch_overhead_pct gauge (ISSUE 15): the registry's
        roofline device time for the executable this round dispatched
        vs the round's measured wall. GR006 HOT_PATHS: one dict lookup
        + float adds; rounds whose executable has no captured record
        (or no known chip) contribute measurement only and the gauge
        stays honest about its modeled denominator."""
        if self.costs is None:
            return
        self._measured_round_ms += dt_ms
        rec = self.costs.record(name, key)
        if rec is None:
            return
        modeled = rec.modeled_seconds(self.chip, n_chips=self.serving_tp)
        if modeled is not None:
            self._modeled_device_ms += modeled * 1e3

    def _sentinel_observe(self, ms_per_advance: float) -> None:
        """Feed the perf sentinel one DECODE-SCAN round's per-token-
        advance latency — the one homogeneous series (mixed and spec
        rounds are excluded at their call sites: prefill interference
        and accept-rate drift are not hardware regressions); a TRIP
        auto-dumps the flight ring through the same postmortem path as
        poison. GR006 HOT_PATHS: host floats; the dump runs only on
        the (rare) trip."""
        if self._sentinel is None:
            return
        if self._sentinel.observe(ms_per_advance, step=self._rounds):
            self.recorder.note_counters(self.counters())
            self.recorder.dump(
                self.record_dir,
                self._artifact_tag("perf-regression"),
                extra={"trip": self._sentinel.trips,
                       "threshold_ms": round(
                           self._sentinel.last_threshold, 3),
                       "round": self._rounds})

    def _decode_round(self, t0: float, prefill_tokens: int = 0) -> bool:
        """One jitted scan of up to `step_horizon` decode steps over
        every live slot (the decode-only round). The horizon is clamped
        to the nearest slot completion (so no request overruns its
        budget mid-scan) and bucketed to a power of two (bounded trace
        count). `prefill_tokens` is the device prefill _admit() ran
        inside this round (whole-prompt mode) — its stall is inside
        this round's wall time, so the audit entry must carry it."""
        live = [i for i, s in enumerate(self._slots) if s.req is not None]
        if not live:
            return False
        # nearest completion: forced tokens still owed + sampling budget
        remaining = min(
            len(self._slots[i].forced) + self._slots[i].req
            .tokens_to_generate - self._slots[i].generated
            for i in live)
        hor = min(self.step_horizon, max(remaining, 1))
        hor = 1 << (hor.bit_length() - 1)  # pow2 bucket
        # windowed lazy allocation (ISSUE 19): the scan writes hor
        # tokens past each live length — the frontier must hold real
        # pages BEFORE dispatch (no-op for non-window engines)
        for i in live:
            self._ensure_pages(i, self._lengths[i] + hor)

        n = self.slots
        active = np.zeros(n, bool)
        forced = np.zeros((n, hor), np.int32)
        use_forced = np.zeros((n, hor), bool)
        greedy = np.ones(n, bool)
        temperature = np.ones(n, np.float32)
        top_k = np.zeros(n, np.int32)
        top_p = np.zeros(n, np.float32)
        seeds = np.zeros(n, np.uint32)
        sample_steps = np.zeros(n, np.int32)
        for i in live:
            s = self._slots[i]
            r = s.req
            active[i] = True
            nf = min(len(s.forced), hor)
            if nf:
                forced[i, :nf] = [s.forced[t] for t in range(nf)]
                use_forced[i, :nf] = True
            greedy[i] = r.greedy
            temperature[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            seeds[i] = np.uint32(r.seed & 0xFFFFFFFF)
            sample_steps[i] = s.sample_step

        all_greedy = all(self._slots[i].req.greedy for i in live)
        (chosen, chosen_lp, new_logits, self._pools_k, self._pools_v,
         self._pools_ks, self._pools_vs) = \
            self._step_fn(hor, all_greedy)(
                self._dec_params, self._pools_k, self._pools_v,
                self._pools_ks, self._pools_vs,
                self._dev(self._pt), self._dev(self._lengths),
                self._last_logits, self._dev(active),
                self._dev(forced), self._dev(use_forced),
                self._dev(greedy), self._dev(temperature),
                self._dev(top_k), self._dev(top_p),
                self._dev(seeds), self._dev(sample_steps),
            )
        self._last_logits = new_logits
        chosen = np.asarray(chosen)  # (slots, hor) — the scheduler's
        # own data dependency: the next round cannot be built without it
        # P0 (graft-check GR006 dogfood): the logprob matrix is an EXTRA
        # per-round device->host transfer that most serving traffic
        # (return_log_probs=False) never reads — fetch it only when some
        # live request actually asked
        want_lp = any(self._slots[i].req.return_log_probs for i in live)
        chosen_lp = np.asarray(chosen_lp) if want_lp else None
        self._steps += hor

        now = time.perf_counter()
        for t in range(hor):
            for i in live:
                s = self._slots[i]
                r = s.req
                if r is None:
                    continue  # retired earlier in this horizon (eod)
                self._lengths[i] += 1
                if r.return_log_probs:
                    r.log_probs.append(float(chosen_lp[i, t]))
                if s.forced:
                    s.forced.popleft()  # prompt token, already in tokens
                    continue
                self._book_token(i, int(chosen[i, t]), now)
        t1 = time.perf_counter()
        dt_ms = (t1 - t0) * 1e3
        with self._lock:  # counters() reads these windows concurrently
            self._round_log.append({
                "prefill_tokens": prefill_tokens, "decode_steps": hor,
                "decode_slots": len(live), "ms": dt_ms})
            # per decode-token-advance latency: the scan amortizes hor
            # steps (the whole-prompt admission stall, when any, rides
            # this round's wall time — that IS the interference)
            self._decode_ms.append(dt_ms / hor)
        self._hists["serve_decode_round_ms"].observe(dt_ms / hor)
        self._note_dispatch("engine.decode_scan", (hor, all_greedy),
                            dt_ms)
        self._sentinel_observe(dt_ms / hor)
        self.tracer.complete("round.decode_scan", t0, t1,
                             round=self._rounds, horizon=hor,
                             decode_slots=len(live),
                             prefill_tokens=prefill_tokens)
        self.recorder.record("round.decode_scan", round=self._rounds,
                             horizon=hor, decode_slots=len(live),
                             prefill_tokens=prefill_tokens,
                             ms=round(dt_ms, 3))
        return True

    def _mixed_round(self):
        """One mixed prefill+decode round (chunked admission): the
        OLDEST admitting slot (FIFO by rid — bounds per-round prefill
        tokens to ONE chunk <= the budget) contributes a ragged prompt
        span resumed at its saved offset; every fully-prefilled live
        slot contributes one decode token; other admitting slots sit
        idle (chunk_lens 0). One jitted dispatch serves all of it.
        Returns (decode slots advanced, prefill tokens consumed, the
        chunk request's rid — the round's trace-span correlation
        key — and the (width, greedy) executable key the round's
        dispatch-overhead accounting reads)."""
        n = self.slots
        pref = [i for i, s in enumerate(self._slots) if s.prefilling]
        ci = min(pref, key=lambda i: self._slots[i].req.rid)
        s_c = self._slots[ci]
        remaining = len(s_c.req.prompt) - s_c.prefill_pos
        width = self._chunk_width(remaining)
        ln = min(remaining, width)
        dec = [i for i, s in enumerate(self._slots)
               if s.req is not None and not s.prefilling]
        # windowed lazy allocation (ISSUE 19): this round scatters the
        # chunk's ln tokens (and one decode token per live slot) past
        # the frontiers — top them up before dispatch
        self._ensure_pages(ci, self._lengths[ci] + ln)
        for i in dec:
            self._ensure_pages(i, self._lengths[i] + 1)

        chunk_tokens = np.zeros((n, width), np.int32)
        chunk_lens = np.zeros((n,), np.int32)
        is_prefill = np.zeros((n,), bool)
        greedy = np.ones(n, bool)
        temperature = np.ones(n, np.float32)
        top_k = np.zeros(n, np.int32)
        top_p = np.zeros(n, np.float32)
        seeds = np.zeros(n, np.uint32)
        sample_steps = np.zeros(n, np.int32)
        chunk_tokens[ci, :ln] = s_c.req.prompt[
            s_c.prefill_pos:s_c.prefill_pos + ln]
        chunk_lens[ci] = ln
        is_prefill[ci] = True
        for i in dec:
            r = self._slots[i].req
            chunk_lens[i] = 1
            greedy[i] = r.greedy
            temperature[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            seeds[i] = np.uint32(r.seed & 0xFFFFFFFF)
            sample_steps[i] = self._slots[i].sample_step
        all_greedy = all(self._slots[i].req.greedy for i in dec)

        (first, first_lp, chunk_lps, new_last, self._pools_k,
         self._pools_v, self._pools_ks, self._pools_vs) = \
            self._mixed_fn(width, all_greedy)(
            self._dec_params, self._pools_k, self._pools_v,
            self._pools_ks, self._pools_vs,
            self._dev(self._pt), self._dev(self._lengths),
            self._last_logits, self._dev(chunk_tokens),
            self._dev(chunk_lens), self._dev(is_prefill),
            self._dev(ci, np.int32),
            self._dev(greedy), self._dev(temperature),
            self._dev(top_k), self._dev(top_p),
            self._dev(seeds), self._dev(sample_steps),
        )
        self._last_logits = new_last
        first = np.asarray(first)
        # P0 (graft-check GR006 dogfood): logprob outputs transfer only
        # when a live request asked for them — the mixed round is the
        # chunked-prefill interference path the decode-p95 gauge
        # watches, so every needless per-round transfer counts
        want_lp = (s_c.req.return_log_probs
                   or any(self._slots[i].req.return_log_probs
                          for i in dec))
        first_lp = np.asarray(first_lp) if want_lp else None
        chunk_lps = (np.asarray(chunk_lps)
                     if s_c.req.return_log_probs else None)
        self._steps += 1
        self._prefill_tokens += ln

        # prefill slot: advance the saved offset, book prompt logprobs
        # (position p predicts prompt token p+1; the chunk's first token
        # was predicted by last round's final logits = first_lp)
        r = s_c.req
        if r.return_log_probs:
            if s_c.prefill_pos > 0:
                r.log_probs.append(float(first_lp[ci]))
            if ln > 1:
                r.log_probs.extend(
                    float(x) for x in chunk_lps[:ln - 1])
        s_c.prefill_pos += ln
        s_c.prefilled += ln
        self._lengths[ci] += ln
        # every prompt page this chunk completed becomes a shareable
        # cache entry (no-op without the prefix cache)
        self._register_prefix(ci)

        # decode slots: one token each, the scan-path bookkeeping at
        # horizon 1
        now = time.perf_counter()
        for i in dec:
            r = self._slots[i].req
            self._lengths[i] += 1
            if r.return_log_probs:
                r.log_probs.append(float(first_lp[i]))
            self._book_token(i, int(first[i]), now)
        return len(dec), ln, s_c.req.rid, (width, all_greedy)

    # -- prefix sharing ----------------------------------------------------

    def _register_prefix(self, si: int) -> None:
        """Register every COMPLETED full prompt page of slot `si` in
        the prefix cache (called as chunked prefill passes each page
        boundary): a later request sharing the prefix hits these pages
        even while this one is still mid-flight. Only pages whose
        tokens are ENTIRELY prompt are registered — a page that also
        receives decode writes is request-specific. Shared pages mapped
        at admission arrive pre-counted in slot.registered; an insert
        lost to a concurrent identical prefill leaves the page
        untracked (free-listed at retirement), never double-indexed."""
        if self._prefix is None:
            return
        s = self._slots[si]
        r = s.req
        ps = self.page_size
        limit = min(s.prefill_pos, len(r.prompt))
        while (s.registered + 1) * ps <= limit:
            pg = int(self._pt[si, s.registered])
            self._prefix.insert(r.prompt[: (s.registered + 1) * ps], pg)
            s.registered += 1

    # -- speculative decoding ----------------------------------------------

    def _spec_fn(self, width, all_greedy):
        key = (width, all_greedy)
        if key not in self._spec_fns:
            self._spec_fns[key] = _make_spec_step_fn(
                self.model, self.vocab_size, width, all_greedy,
                contract_key=key, contract_owner=self,
                contract_budget=2)
            self._capture_cost(
                "engine.spec_verify", key, self._spec_fns[key],
                lambda: self._null_spec_args(width))
        return self._spec_fns[key]

    def _draft(self, si: int) -> List[int]:
        """Prompt-lookup (n-gram) drafter: find the most recent earlier
        occurrence of the request's trailing bigram in its own tokens
        (prompt + generated) and propose the continuation — free to
        compute, surprisingly effective on prompts the answer quotes
        (the Saxena prompt-lookup result). Greedy slots only: sampled
        verification would need rejection-sampling machinery for
        distribution parity. Drafts are capped so the verify chunk
        never writes a position past the request's reserved prompt +
        tokens_to_generate page reach."""
        s = self._slots[si]
        r = s.req
        if not r.greedy:
            return []
        cap = min(self.spec_decode_k,
                  r.tokens_to_generate - s.generated - 1)
        if self.window is not None:
            # window edge (ISSUE 19): keep the whole verify chunk
            # inside one window of its first position, so every chunk
            # row still attends the round's carried context
            cap = min(cap, self.window - 1)
        if cap <= 0:
            return []
        toks = r.tokens
        if len(toks) < 3:
            return []
        # fold newly-booked tokens into the bigram index; every start
        # j <= len-3 is interior (the trailing bigram at len-2 stays
        # out, or the lookup below would match itself)
        while s.bigram_next <= len(toks) - 3:
            j = s.bigram_next
            occ = s.bigram.setdefault((toks[j], toks[j + 1]), [])
            occ.append(j)
            if len(occ) > 8:
                del occ[0]
            s.bigram_next += 1
        # position len(toks) is decided by the carried logits inside
        # the round, so the continuation shifts by one: drafts cover
        # the positions after it. Prefer the newest occurrence whose
        # continuation fills the cap; on short-period repetition the
        # newest ones sit at the tail with truncated continuations, so
        # fall back to the longest available.
        occ = s.bigram.get((toks[-2], toks[-1]))
        if not occ:
            return []
        best_j, best_avail = None, 0
        for j in reversed(occ):
            avail = len(toks) - (j + 3)
            if avail >= cap:
                best_j, best_avail = j, avail
                break
            if avail > best_avail:
                best_j, best_avail = j, avail
        if best_j is None:
            return []
        return list(toks[best_j + 3: best_j + 3 + cap])

    def _collect_drafts(self) -> dict:
        """Drafts for every eligible live slot; empty dict means 'run a
        plain decode round'. No spec round while any slot still owes
        teacher-forced prompt tokens (whole-prompt mode's post-bucket
        tail): the spec step has no forcing machinery, and a sampled
        token where a forced one belongs would corrupt the stream."""
        if any(s.req is not None and s.forced for s in self._slots):
            return {}
        drafts = {}
        for i, s in enumerate(self._slots):
            if s.req is None:
                continue
            d = self._draft(i)
            if d:
                drafts[i] = d
        return drafts

    def _spec_round(self, drafts: dict, t0: float,
                    prefill_tokens: int = 0) -> None:
        """One speculative round: every live slot contributes a ragged
        chunk — spec slots [next token + draft run], the rest plain
        width-1 decode rows — through ONE jitted width-(k+1) dispatch.
        The device verifies drafts against its own greedy targets
        (_make_spec_step_fn); the host books the first token plus the
        accepted run and rolls the slot's length mirror forward by
        exactly the booked count, which IS the rejection rollback (the
        next round's writes overwrite stale K/V past it)."""
        width = self.spec_decode_k + 1
        n = self.slots
        live = [i for i, s in enumerate(self._slots) if s.req is not None]
        # windowed lazy allocation (ISSUE 19): the verify chunk writes
        # up to 1 + len(draft) tokens past each live frontier
        for i in live:
            self._ensure_pages(
                i, self._lengths[i] + 1 + len(drafts.get(i, [])))
        chunk_tokens = np.zeros((n, width), np.int32)
        chunk_lens = np.zeros((n,), np.int32)
        is_spec = np.zeros((n,), bool)
        greedy = np.ones(n, bool)
        temperature = np.ones(n, np.float32)
        top_k = np.zeros(n, np.int32)
        top_p = np.zeros(n, np.float32)
        seeds = np.zeros(n, np.uint32)
        sample_steps = np.zeros(n, np.int32)
        for i in live:
            s = self._slots[i]
            r = s.req
            d = drafts.get(i, [])
            if d:
                chunk_tokens[i, 1:1 + len(d)] = d
            chunk_lens[i] = 1 + len(d)
            is_spec[i] = bool(d)
            greedy[i] = r.greedy
            temperature[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            seeds[i] = np.uint32(r.seed & 0xFFFFFFFF)
            sample_steps[i] = s.sample_step
        all_greedy = all(self._slots[i].req.greedy for i in live)
        (first, first_lp, gt, gt_lp, acc, new_last, self._pools_k,
         self._pools_v, self._pools_ks, self._pools_vs) = \
            self._spec_fn(width, all_greedy)(
            self._dec_params, self._pools_k, self._pools_v,
            self._pools_ks, self._pools_vs,
            self._dev(self._pt), self._dev(self._lengths),
            self._last_logits, self._dev(chunk_tokens),
            self._dev(chunk_lens), self._dev(is_spec),
            self._dev(greedy), self._dev(temperature),
            self._dev(top_k), self._dev(top_p),
            self._dev(seeds), self._dev(sample_steps),
        )
        self._last_logits = new_last
        first = np.asarray(first)
        gt = np.asarray(gt)
        acc = np.asarray(acc)
        # P0 (graft-check GR006 dogfood): the two logprob matrices are
        # EXTRA per-round device->host transfers that logprob-less
        # traffic (the common case) never reads — fetch them only when
        # some live request actually asked
        want_lp = any(self._slots[i].req.return_log_probs for i in live)
        first_lp = np.asarray(first_lp) if want_lp else None
        gt_lp = np.asarray(gt_lp) if want_lp else None
        self._steps += 1
        self._spec_rounds += 1

        now = time.perf_counter()
        emitted_total = 0
        for i in live:
            s = self._slots[i]
            r = s.req
            d_n = int(chunk_lens[i]) - 1
            a = int(acc[i]) if d_n else 0
            self._spec_proposed += d_n
            # the round's first token (decided from the carried logits,
            # exactly a decode row), then the accepted draft run — each
            # accepted token IS the greedy target the decode scan would
            # have produced at that position
            emit = [(int(first[i]),
                     float(first_lp[i]) if want_lp else 0.0)]
            emit += [(int(gt[i, j]),
                      float(gt_lp[i, j]) if want_lp else 0.0)
                     for j in range(a)]
            booked = 0
            for j, (tok, lp) in enumerate(emit):
                self._lengths[i] += 1
                if r.return_log_probs:
                    r.log_probs.append(lp)
                if j > 0:
                    # per-request spec accounting for the retire cost
                    # record: BEFORE _book_token, which may retire the
                    # slot (resetting its counters) on eod/budget
                    s.spec_accepted += 1
                booked += 1
                if self._book_token(i, tok, now):
                    break  # eod/budget: stale chunk tail never books
            emitted_total += booked
            # acceptance gauge counts only draft tokens actually BOOKED
            # (booked minus the first decode-row token): eod/budget can
            # retire the slot mid-run, and the unbooked accepted tail
            # must not inflate serve_spec_accept_rate — operators read
            # that gauge to decide whether spec decode pays for itself
            self._spec_accepted += booked - 1

        t1 = time.perf_counter()
        dt_ms = (t1 - t0) * 1e3
        per_advance = dt_ms * len(live) / max(emitted_total, 1)
        with self._lock:  # counters() reads these windows concurrently
            # prefill_tokens: whole-prompt-mode _admit() ran its device
            # prefill inside this round's wall time (the _decode_round
            # contract) — the audit trail must carry it here too
            self._round_log.append({
                "prefill_tokens": prefill_tokens, "decode_steps": 1,
                "decode_slots": len(live), "ms": dt_ms,
                "spec_emitted": emitted_total})
            # per decode-token advance: one spec round advances
            # emitted/live tokens per slot
            self._decode_ms.append(per_advance)
        self._hists["serve_decode_round_ms"].observe(per_advance)
        self._note_dispatch("engine.spec_verify", (width, all_greedy),
                            dt_ms)
        # NOT fed to the sentinel (same reasoning as mixed rounds): a
        # spec round's per-advance latency moves with the ACCEPT RATE
        # — adversarial prompts dropping acceptance would read as a
        # hardware regression against a decode-scan baseline. The
        # sentinel watches the one homogeneous series (decode-scan
        # per-token-advance); acceptance drift is serve_spec_accept_
        # rate's job.
        self.tracer.complete("round.spec_verify", t0, t1,
                             round=self._rounds, decode_slots=len(live),
                             emitted=emitted_total,
                             drafted=len(drafts))
        self.recorder.record("round.spec_verify", round=self._rounds,
                             decode_slots=len(live),
                             emitted=emitted_total, drafted=len(drafts),
                             ms=round(dt_ms, 3))

    def drain(self):
        """Run until the queue and every slot are empty."""
        while self.step():
            pass

    def reset_prefix_cache(self):
        """Drop every cached prefix and return its pages to the free
        list. Only legal on an IDLE engine (no live slots): a live slot
        holding refcounted shared pages would double-free them at
        retirement once the owning cache is gone. Benchmarks use this
        to measure a cold cache on a compile-warmed engine."""
        if self._prefix is None:
            return
        busy = [i for i, s in enumerate(self._slots) if s.req is not None]
        if busy:
            raise RuntimeError(
                f"reset_prefix_cache on a busy engine (slots {busy} "
                f"live): drain() first")
        self._free_pages.extend(self._prefix.evict(self.num_pages))
        assert self._prefix.cached_pages == 0
        self._prefix = PrefixCache(self.page_size)

    # -- cross-replica KV page hand-off (ISSUE 17) -------------------------
    # Disaggregated serving's transfer pair: a prefill replica exports
    # the full-page prefix of a finished prompt as a self-contained
    # host payload; a decode replica imports it into freshly allocated
    # pages and registers the chain on its PrefixCache, so the next
    # submit() of that prompt admits as a prefix HIT and decodes
    # without prefilling. Both sides funnel through the transfer inbox
    # (`_xfers`): the serve loop donates the page pools every round and
    # the PrefixCache is serve-thread-only, so the actual pool work
    # always runs on the serve thread (or inline when no serve thread
    # exists — manual-step tests and bench setup).

    def export_prefix(self, prompt: List[int]):
        """Export the cached full-page prefix of `prompt` as a host
        payload dict, or None when this engine's PrefixCache holds no
        full page of it (never prefilled here, or already evicted).
        The donor's pages stay registered and unreferenced — shipping
        is a read, and LRU eviction reclaims them under pressure, so a
        hand-off that dies on the receiving side needs no donor-side
        cleanup at all."""
        if self._prefix is None:
            raise ValueError(
                "export_prefix needs prefix_cache=True: the transfer "
                "ships the cache's registered pages")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("export_prefix: empty prompt")
        return self._run_transfer({"kind": "export", "prompt": prompt})

    def import_prefix(self, payload):
        """Splice an exported prefix payload into this engine's pool:
        allocate pages (evicting idle cache entries if short), scatter
        the payload rows in one jitted dispatch, and register the chain
        on the PrefixCache refcounted exactly like locally prefilled
        pages. Returns {'pages': shipped, 'registered': retained} on
        success; False when the pool stayed short after eviction (the
        caller falls back to prefilling locally). Geometry/dtype
        mismatches (page size, kv dtype, layer shapes) raise
        ValueError: splicing incompatible pages would poison decode."""
        if self._prefix is None:
            raise ValueError(
                "import_prefix needs prefix_cache=True: transferred "
                "pages land as cache entries")
        self._check_payload(payload)
        return self._run_transfer({"kind": "import", "payload": payload})

    def _check_payload(self, payload) -> None:
        """Receiver-side compatibility gate, on the CALLER's thread so
        a bad payload fails fast instead of poisoning the serve loop."""
        if not isinstance(payload, dict):
            raise ValueError("import_prefix: payload must be the dict "
                             "export_prefix produced")
        n = int(payload.get("pages", 0))
        ps = int(payload.get("page_size", 0))
        toks = payload.get("tokens") or []
        if n < 1 or n > self.max_pages_per_slot:
            raise ValueError(
                f"import_prefix: {n} pages outside [1, "
                f"{self.max_pages_per_slot}] for this engine")
        if ps != self.page_size:
            raise ValueError(
                f"import_prefix: payload page_size {ps} != engine "
                f"page_size {self.page_size}")
        if len(toks) != n * ps:
            raise ValueError(
                f"import_prefix: {len(toks)} prefix tokens for {n} "
                f"pages of {ps}")
        if str(payload.get("dtype")) != self.kv_pool_dtype():
            raise ValueError(
                f"import_prefix: payload kv dtype "
                f"{payload.get('dtype')} != pool "
                f"{self.kv_pool_dtype()} — a cross-dtype splice would "
                f"decode garbage")
        for name, pools in (("k", self._pools_k), ("v", self._pools_v),
                            ("ks", self._pools_ks),
                            ("vs", self._pools_vs)):
            rows = payload.get(name) or []
            if len(rows) != len(pools):
                raise ValueError(
                    f"import_prefix: {len(rows)} '{name}' layer blocks "
                    f"vs {len(pools)} pools (int8 (data, scale) pairs "
                    f"must travel together)")
            for i, (r, p) in enumerate(zip(rows, pools)):
                if tuple(r.shape[1:]) != tuple(p.shape[1:]):
                    raise ValueError(
                        f"import_prefix: '{name}' layer {i} page shape "
                        f"{tuple(r.shape[1:])} != pool "
                        f"{tuple(p.shape[1:])}")

    def _run_transfer(self, op: dict):
        """Apply `op` on the serve thread (inbox + wake + wait) or
        inline when no serve loop is running. Waiters poll the engine's
        liveness so a poisoned loop fails the hand-off instead of
        hanging the router's orchestration thread."""
        op["done"] = threading.Event()
        op["result"] = None
        op["error"] = None
        with self._lock:
            alive = (self._thread is not None and self._running
                     and self._broken is None)
            if alive:
                self._xfers.append(op)
                self._work.notify()
        if not alive:
            with self.mesh_scope():
                self._apply_transfer(op)
        else:
            while not op["done"].wait(timeout=0.05):
                if self._broken is not None or self._thread is None \
                        or not self._thread.is_alive():
                    # the loop died with the op possibly still queued;
                    # _fail_all also sweeps the inbox, so either way:
                    if not op["done"].is_set():
                        raise RuntimeError(
                            f"page transfer failed: engine "
                            f"{'broken: ' + self._broken if self._broken else 'stopped'}")
        if op["error"] is not None:
            raise op["error"]
        return op["result"]

    def _apply_transfers(self) -> bool:
        """Serve-thread inbox drain (top of every scheduler round)."""
        did = False
        while True:
            with self._lock:
                if not self._xfers:
                    return did
                op = self._xfers.popleft()
            self._apply_transfer(op)
            did = True

    def _fail_transfers(self, msg: str) -> None:
        while True:
            with self._lock:
                if not self._xfers:
                    return
                op = self._xfers.popleft()
            op["error"] = RuntimeError(msg)
            op["done"].set()

    def _apply_transfer(self, op: dict) -> None:
        try:
            if op["kind"] == "export":
                op["result"] = self._do_export(op["prompt"])
            else:
                op["result"] = self._do_import(op["payload"])
        except Exception as e:  # noqa: BLE001 — the waiter re-raises;
            # a transfer failure must never poison the serve loop
            op["error"] = e
        op["done"].set()

    def _do_export(self, prompt: List[int]):
        match = self._prefix.lookup(prompt)
        n = match.full_pages
        if n <= 0:
            return None
        # pin against eviction across the gather (serve-thread-local
        # today, but the pin is what makes that an implementation
        # detail rather than a liveness assumption)
        self._prefix.acquire(match)
        try:
            ids = np.zeros(self.max_pages_per_slot, np.int32)
            ids[:n] = match.pages[:n]
            rows_k, rows_v, rows_ks, rows_vs = self._export_fn(
                self._pools_k, self._pools_v, self._pools_ks,
                self._pools_vs, self._dev(ids))

            def host(rows):
                return [np.asarray(r)[:n] for r in rows]

            payload = {
                "tokens": list(prompt[: n * self.page_size]),
                "pages": n,
                "page_size": self.page_size,
                "dtype": self.kv_pool_dtype(),
                "k": host(rows_k), "v": host(rows_v),
                "ks": host(rows_ks), "vs": host(rows_vs),
            }
        finally:
            self._prefix.unacquire(match)
        self._transfers_out += 1
        self._transfer_pages_out += n
        self.recorder.record("xfer.export", pages=n,
                             tokens=len(payload["tokens"]))
        return payload

    def _do_import(self, payload):
        n = int(payload["pages"])
        if n > len(self._free_pages):
            self._free_pages.extend(
                self._prefix.evict(n - len(self._free_pages)))
        if n > len(self._free_pages):
            return False  # pool full of LIVE pages: prefill locally
        pages = [self._free_pages.pop() for _ in range(n)]
        P = self.max_pages_per_slot
        ids = np.zeros(P, np.int32)
        ids[:n] = pages

        def pad(rows, pools):
            out = []
            for r, p in zip(rows, pools):
                block = np.zeros((P,) + tuple(p.shape[1:]),
                                 np.dtype(p.dtype))
                block[:n] = r
                out.append(self._dev(block))
            return tuple(out)

        (self._pools_k, self._pools_v, self._pools_ks,
         self._pools_vs) = self._import_fn(
            self._pools_k, self._pools_v, self._pools_ks,
            self._pools_vs, self._dev(ids),
            pad(payload["k"], self._pools_k),
            pad(payload["v"], self._pools_v),
            pad(payload["ks"], self._pools_ks),
            pad(payload["vs"], self._pools_vs))
        rejected = self._prefix.insert_chain(
            [int(t) for t in payload["tokens"]], pages)
        self._free_pages.extend(rejected)
        registered = n - len(rejected)
        self._transfers_in += 1
        self._transfer_pages_in += registered
        self.recorder.record("xfer.import", pages=n,
                             registered=registered)
        return {"pages": n, "registered": registered}

    # -- modeled backlog / admission (ISSUE 17) ----------------------------

    def modeled_request_flops(self, prompt_tokens: int,
                              gen_tokens: int, start: int = 0):
        """Modeled device FLOPs to finish one request from cache length
        `start`: the same analytic integral the per-request cost record
        uses (linear 2N per computed token + attention 4*L*h per cached
        position, integrated over context growth). None when the cost
        registry is off — callers must fall back to occupancy signals,
        not model against zero coefficients."""
        if self.costs is None:
            return None
        final = prompt_tokens + gen_tokens
        start = min(max(int(start), 0), final)
        return (self._cost_fpt_linear * (final - start)
                + 0.5 * self._cost_attn_coeff
                * (float(final) ** 2 - float(start) ** 2))

    def modeled_backlog_flops(self):
        """Total modeled FLOPs queued on this engine: every queued
        request priced from zero, every live slot priced from its
        current cache length. The router's placement signal (ISSUE 17)
        — replaces raw queue_depth + slots_busy, which weighs a 4k-token
        prefill and a 12-token completion identically."""
        if self.costs is None:
            return None
        total = 0.0
        with self._lock:
            work = [(len(r.prompt), r.tokens_to_generate, 0)
                    for r in self._queue]
            for i, s in enumerate(self._slots):
                r = s.req
                if r is not None:
                    work.append((len(r.prompt), r.tokens_to_generate,
                                 int(self._lengths[i])))
        for plen, gen, start in work:
            total += self.modeled_request_flops(plen, gen, start)
        return total

    def modeled_backlog_seconds(self):
        """Modeled wall seconds to drain this engine's backlog at the
        chip's roofline: backlog FLOPs / (peak FLOP/s x serving_tp).
        None without a cost registry AND a credible chip spec — an SLO
        decision against a guessed peak would be dishonest, so callers
        degrade to the constant fallback instead."""
        fl = self.modeled_backlog_flops()
        if fl is None or self.chip is None:
            return None
        dtype = "int8" if self.quantize_weights else "bf16"
        rate = self.chip.peak_flops_for(dtype) * max(self.serving_tp, 1)
        return fl / max(rate, 1.0)

    def retry_after_s(self) -> float:
        """Honest Retry-After (ISSUE 17 satellite): the modeled drain
        time of the current backlog, clamped to [1, 60] s; constant 1 s
        when the cost registry is off (the pre-ISSUE-17 behaviour,
        pinned by tests/test_server.py)."""
        s = self.modeled_backlog_seconds()
        if s is None:
            return 1.0
        return float(min(max(s, 1.0), 60.0))

    # -- background serve loop --------------------------------------------

    def _fail_all(self, msg: str):
        """Fail every queued and in-flight request (fatal step error or
        non-drain stop) so no waiter hangs on a dead engine."""
        self._fail_transfers(msg)
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending:
            req.error = msg
            self._finish(req)
        for i, s in enumerate(self._slots):
            if s.req is not None:
                s.req.error = msg
                self._retire(i)

    # -- idle-round example args (ONE construction for warmup, the AOT
    # audit, and mint-time cost capture — three consumers of the same
    # shapes that previously each hand-built them, ISSUE 15 refactor).
    # All-zero page-table rows route every K/V write to the dead null
    # page; the live pools ride the args so what is traced/lowered is
    # exactly what traffic runs.

    def _null_scan_args(self, h: int) -> tuple:
        n = self.slots
        zeros_i = self._dev(np.zeros((n,), np.int32))
        return (self._dec_params, self._pools_k, self._pools_v,
                self._pools_ks, self._pools_vs,
                self._dev(np.zeros_like(self._pt)), zeros_i,
                self._last_logits,
                self._dev(np.zeros(n, bool)),
                self._dev(np.zeros((n, h), np.int32)),
                self._dev(np.zeros((n, h), bool)),
                self._dev(np.ones(n, bool)),
                self._dev(np.ones(n, np.float32)),
                zeros_i,
                self._dev(np.zeros(n, np.float32)),
                self._dev(np.zeros(n, np.uint32)),
                zeros_i)

    def _null_mixed_args(self, w: int) -> tuple:
        n = self.slots
        zeros_i = self._dev(np.zeros((n,), np.int32))
        return (self._dec_params, self._pools_k, self._pools_v,
                self._pools_ks, self._pools_vs,
                self._dev(np.zeros_like(self._pt)), zeros_i,
                self._last_logits,
                self._dev(np.zeros((n, w), np.int32)),
                zeros_i,
                self._dev(np.zeros(n, bool)),
                self._dev(0, np.int32),
                self._dev(np.ones(n, bool)),
                self._dev(np.ones(n, np.float32)),
                zeros_i,
                self._dev(np.zeros(n, np.float32)),
                self._dev(np.zeros(n, np.uint32)),
                zeros_i)

    def _null_spec_args(self, w: int) -> tuple:
        n = self.slots
        zeros_i = self._dev(np.zeros((n,), np.int32))
        return (self._dec_params, self._pools_k, self._pools_v,
                self._pools_ks, self._pools_vs,
                self._dev(np.zeros_like(self._pt)), zeros_i,
                self._last_logits,
                self._dev(np.zeros((n, w), np.int32)),
                zeros_i,
                self._dev(np.zeros(n, bool)),
                self._dev(np.ones(n, bool)),
                self._dev(np.ones(n, np.float32)),
                zeros_i,
                self._dev(np.zeros(n, np.float32)),
                self._dev(np.zeros(n, np.uint32)),
                zeros_i)

    def _null_prefill_args(self, plen: int) -> tuple:
        return (self._dec_params, self._pools_k, self._pools_v,
                self._pools_ks, self._pools_vs,
                self._dev(np.zeros((1, plen), np.int32)),
                self._dev(self._pt[0]))

    def _null_copy_args(self) -> tuple:
        return (self._pools_k, self._pools_v, self._pools_ks,
                self._pools_vs, self._dev(0, np.int32),
                self._dev(0, np.int32))

    def _null_xfer_ids(self):
        # all-null ids: every row gathers/scatters the dead page 0 —
        # the same idle-round idiom the other _null_*_args use
        return self._dev(
            np.zeros(self.max_pages_per_slot, np.int32))

    def _null_payload_rows(self) -> tuple:
        """Zero payload row blocks shaped like a full-width import —
        one (max_pages_per_slot, ...) block per layer pool, pool
        dtypes, on the engine's devices."""
        P = self.max_pages_per_slot

        def rows(pools):
            return tuple(
                self._dev(np.zeros((P,) + tuple(p.shape[1:]),
                                   np.dtype(p.dtype))) for p in pools)

        return (rows(self._pools_k), rows(self._pools_v),
                rows(self._pools_ks), rows(self._pools_vs))

    def _null_export_args(self) -> tuple:
        return (self._pools_k, self._pools_v, self._pools_ks,
                self._pools_vs, self._null_xfer_ids())

    def _null_import_args(self) -> tuple:
        rk, rv, rks, rvs = self._null_payload_rows()
        return (self._pools_k, self._pools_v, self._pools_ks,
                self._pools_vs, self._null_xfer_ids(), rk, rv, rks, rvs)

    def warmup(self):
        """Pre-trace every step executable the configured buckets can
        reach — the pow2 decode-scan horizons and (chunked mode) the
        pow2 mixed-step widths, greedy-specialized (the serving hot
        path) — so the first request never eats a compile stall.
        Warmup rounds run with every slot idle against the REAL pools:
        all K/V writes land on the dead null page (all-zero page-table
        rows), lengths are untouched on the host, and the returned
        last_logits is discarded, so warmup is invisible to traffic.
        Opt-in: `warmup_compile=True` runs it inside `start()`."""
        with self.mesh_scope():
            self._warmup_scoped()

    def _warmup_scoped(self):
        for h in horizon_buckets(self.step_horizon):
            (_, _, _, self._pools_k, self._pools_v, self._pools_ks,
             self._pools_vs) = self._step_fn(h, True)(
                *self._null_scan_args(h))
        if self.prefill_chunk_tokens:
            for w in mixed_width_buckets(self.prefill_chunk_tokens):
                (_, _, _, _, self._pools_k, self._pools_v,
                 self._pools_ks, self._pools_vs) = \
                    self._mixed_fn(w, True)(*self._null_mixed_args(w))
        if self.spec_decode_k:
            w = self.spec_decode_k + 1
            (_, _, _, _, _, _, self._pools_k, self._pools_v,
             self._pools_ks, self._pools_vs) = \
                self._spec_fn(w, True)(*self._null_spec_args(w))
        if self._prefix is not None:
            # hand-off pair (ISSUE 17): the first cross-replica
            # transfer must not eat a compile stall mid-burst. The
            # null import scatters zero rows into the dead null page
            # only (all-null ids), so like every other warmup dispatch
            # it is invisible to traffic; pools are reassigned from
            # the donated outputs.
            self._export_fn(*self._null_export_args())
            (self._pools_k, self._pools_v, self._pools_ks,
             self._pools_vs) = self._import_fn(*self._null_import_args())

    def audit_entry_points(self):
        """(contract name, jitted fn, example args) for every jitted
        entry point this engine's configuration can dispatch — the AOT
        compile-contract audit (analysis/audit.py) lowers each one
        against the REAL pools/params, so what it audits is exactly
        what traffic runs. Args are the same idle-round construction
        warmup() and mint-time cost capture use (the _null_*_args
        helpers); nothing here executes — builders are invoked (minting
        variants within the engine's own budgets) but the returned fns
        are only lowered.

        On a tp mesh the caller must ALSO lower under `mesh_scope()`
        (analysis/audit.py does): the constraints bake at trace time,
        and the tp2 audit rows exist to pin exactly that program."""
        h = horizon_buckets(self.step_horizon)[-1]
        out = [("engine.decode_scan", self._step_fn(h, True),
                self._null_scan_args(h))]
        if self.prefill_chunk_tokens:
            w = mixed_width_buckets(self.prefill_chunk_tokens)[-1]
            out.append(("engine.mixed_step", self._mixed_fn(w, True),
                        self._null_mixed_args(w)))
        plen = bucket_prefill_len(min(8, self.max_context))
        out.append(("engine.prefill_bucket", self._prefill_fn(plen),
                    self._null_prefill_args(plen)))
        if self.spec_decode_k:
            w = self.spec_decode_k + 1
            out.append(("engine.spec_verify", self._spec_fn(w, True),
                        self._null_spec_args(w)))
        out.append(("engine.page_copy", self._copy_fn,
                    self._null_copy_args()))
        out.append(("engine.page_export", self._export_fn,
                    self._null_export_args()))
        out.append(("engine.page_import", self._import_fn,
                    self._null_import_args()))
        return out

    def start(self):
        assert self._thread is None, "engine already started"
        # startup capacity log (ISSUE 9): the kv_dtype decision and
        # what it buys, in the operator's units — mirrors the
        # serve_kv_* gauges on GET /metrics
        # capacity numbers are PER CHIP from live shardings (ISSUE 14
        # small fix): on a tp mesh the group-sharded pools cost 1/tp
        # per chip, and this log is what operators size against HBM
        _logger.info(
            "decode engine%s: %d slots, paged KV pool kv_dtype=%s%s — "
            "%d pages x %d tokens = %d KV positions, %.1f MiB/chip "
            "pool (%d bytes/token/chip)%s%s",
            "" if self.replica_id is None
            else f" [replica {self.replica_id}]",
            self.slots, self.kv_pool_dtype(),
            "" if self.serving_tp == 1
            else f" tp={self.serving_tp} (group-sharded)",
            self.num_pages - 1,
            self.page_size, (self.num_pages - 1) * self.page_size,
            self.kv_pool_bytes() / 2**20, self.kv_bytes_per_token(),
            ", weight-only int8 decode matmuls"
            if self.quantize_weights else "",
            "" if self.kv_dtype == "bf16" else
            " [fp default off: greedy parity is measured drift, not "
            "bitwise — see docs/GUIDE.md 'Quantized serving']",
        )
        if self.window is not None:
            # windowed capacity (ISSUE 19): what a long slot actually
            # costs — the operator sizes page_budget against THIS bound
            # per concurrent slot, not against max_context
            _logger.info(
                "sliding-window serving: window=%d tokens — peak "
                "%d pages/slot (vs %d at full max_context reach); "
                "out-of-window pages reclaim mid-flight "
                "(serve_window_reclaimed_pages on /metrics)",
                self.window, self._window_slot_pages(),
                self.max_pages_per_slot)
        if self.warmup_compile:
            self.warmup()
        self._running = True

        def loop():
            while self._running:
                try:
                    did = self.step()
                except Exception as e:  # noqa: BLE001 — a dead serve
                    # loop with hung waiters is strictly worse than any
                    # error it could swallow: fail every request LOUDLY
                    # and refuse new ones
                    self._broken = f"engine step failed: {e!r}"
                    _logger.exception("serve loop died; failing all "
                                      "in-flight requests")
                    # flight-recorder postmortem (ISSUE 13): the last-
                    # N-rounds record + live counters, BEFORE _fail_all
                    # clears the slots — the artifact must show what
                    # the engine was doing when it died, keyed by rid
                    self.recorder.record(
                        "poison", error=repr(e), round=self._rounds,
                        queue_depth=len(self._queue),
                        live_rids=[s.req.rid for s in self._slots
                                   if s.req is not None])
                    self.recorder.note_counters(self.counters())
                    self.recorder.dump(
                        self.record_dir,
                        self._artifact_tag("engine-poison"),
                        extra={"costs": self.costs.snapshot()}
                        if self.costs is not None else None)
                    self._stop_profile()
                    self._fail_all(self._broken)
                    self._running = False
                    return
                if not did:
                    with self._work:
                        if self._running:
                            self._work.wait(timeout=0.05)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True):
        """Stop the serve loop; drain=True (graceful) finishes every
        admitted AND queued request first, drain=False fails queued
        requests and abandons running slots."""
        if self._thread is None:
            return
        if drain:
            while self._thread.is_alive() and self._broken is None:
                with self._lock:
                    busy = bool(self._queue) or any(
                        s.req is not None for s in self._slots)
                if not busy:
                    break
                time.sleep(0.005)
        self._running = False
        with self._work:
            self._work.notify_all()
        self._thread.join()
        self._thread = None
        # a transfer enqueued after the loop's last drain would hang
        # its waiter forever — sweep the inbox now the loop is gone
        self._fail_transfers("engine stopped")
        self._stop_profile()  # an interrupted capture still flushes
        if self.trace_dir:
            import os as _os

            path = self.tracer.export(_os.path.join(
                self.trace_dir,
                f"trace_{self._artifact_tag('engine')}_"
                f"{_os.getpid()}.json"))
            if path:
                _logger.info("engine span trace exported to %s "
                             "(Perfetto / chrome://tracing)", path)
        if not drain:
            self._fail_all("engine stopped")

    # -- observability -----------------------------------------------------

    def kv_pool_dtype(self) -> str:
        """The pool's ACTUAL storage dtype (e.g. 'int8', 'bfloat16',
        'float32') — what the gauges report. kv_dtype='bf16' means
        'the model compute dtype', so an fp32-compute deployment
        genuinely stores fp32 pages; reporting the constructor string
        there would contradict the bytes gauges next to it."""
        return str(self._pools_k[0].dtype)

    def kv_pool_bytes(self) -> int:
        """PER-CHIP HBM the paged KV pool holds — data pools plus
        (int8) scale pools, summed over layers, derived from the LIVE
        shardings of the actual allocated arrays (each leaf counts its
        shard shape, not its global shape). On a single chip the two
        are the same number this gauge always reported; on a tp mesh
        the group-sharded pools cost 1/tp per chip, and reporting the
        global bytes here would overstate per-chip capacity by tp×
        (ISSUE 14 small fix — operators size THIS against one chip's
        HBM). Pinned by tests/test_tp_serving.py."""
        total = 0
        for x in (*self._pools_k, *self._pools_v,
                  *self._pools_ks, *self._pools_vs):
            shard = x.sharding.shard_shape(x.shape)
            total += int(np.prod(shard)) * x.dtype.itemsize
        return total

    def kv_bytes_per_token(self) -> int:
        """PER-CHIP KV bytes one cached token costs across all layers
        (K + V data + any scales) — the page-pool sizing number
        operators compare against one chip's HBM (docs/GUIDE.md sizing
        math: ~96 KiB/token bf16 at tp=1, /tp on a serving mesh, ~half
        for int8)."""
        return round(self.kv_pool_bytes()
                     / (self.num_pages * self.page_size))

    @staticmethod
    def _pct(window, p: float) -> float:
        xs = sorted(window)
        if not xs:
            return 0.0
        return xs[min(int(p * len(xs)), len(xs) - 1)]

    def health(self) -> dict:
        """Liveness snapshot for GET /health (inference/server.py): is
        the serve loop running, did it die poisoned (`_broken` carries
        the fatal step error), and how much work is pending. Cheap by
        design — a load balancer polls this."""
        alive = self._thread is not None and self._thread.is_alive()
        return {
            "alive": alive,
            "broken": self._broken,
            "queue_depth": len(self._queue),
            "slots_busy": sum(1 for s in self._slots if s.req is not None),
        }

    def counters(self) -> dict:
        """Live serving counters; exported via `export_gauges` through
        the existing timers-gauge path (training/timers.py) and served
        by the HTTP layer at GET /metrics (inference/server.py). The
        latency gauges are recent-window percentiles (last 256):
        `serve_ttft_*` = submit -> first GENERATED token per request,
        `serve_decode_p95_ms` = wall ms per decode-token advance per
        round — during chunked admission a mixed round IS one decode
        step, so this gauge is the chunked-prefill interference bound
        made visible."""
        occupied = sum(1 for s in self._slots if s.req is not None)
        dt = max(time.perf_counter() - self._t0, 1e-9)
        with self._lock:
            # snapshot the latency windows under the lock (the serve
            # loop appends to them under the same lock): sorting a
            # deque mid-append raises RuntimeError, and GET /metrics
            # must never die mid-traffic
            ttft = list(self._ttft_ms)
            decode_ms = list(self._decode_ms)
        out = {}
        if self.replica_id is not None:
            # replica tag first (ISSUE 14): aggregated /metrics from N
            # replicas stay attributable at the router. ABSENT on
            # standalone engines, so the pre-router JSON schema stays
            # byte-compatible (tests/test_telemetry.py pins it).
            out["serve_replica_id"] = self.replica_id
        out |= {
            # capacity gauges (ISSUE 9): which dtype the pool ACTUALLY
            # stores (kv_pool_dtype — consistent with the bytes gauges
            # by construction), what it costs, and what one token
            # costs — the int8 capacity doubling made visible to
            # operators (timers.gauge takes numbers or strings;
            # /metrics serves both)
            "serve_kv_dtype": self.kv_pool_dtype(),
            "serve_kv_pool_bytes": self.kv_pool_bytes(),
            "serve_kv_bytes_per_token": self.kv_bytes_per_token(),
            "serve_slot_occupancy": occupied / self.slots,
            "serve_queue_depth": len(self._queue),
            "serve_pages_in_use": self.num_pages - 1
            - len(self._free_pages),
            "serve_pages_free": len(self._free_pages),
            "serve_admitted": self._admitted,
            "serve_retired": self._retired,
            "serve_timed_out": self._timed_out,
            "serve_cancelled": self._cancelled,
            "serve_steps": self._steps,
            "serve_tok_s": round(self._tokens_out / dt, 2),
            "serve_prefill_tokens": self._prefill_tokens,
            "serve_ttft_p50_ms": round(self._pct(ttft, 0.50), 2),
            "serve_ttft_p95_ms": round(self._pct(ttft, 0.95), 2),
            "serve_decode_p95_ms": round(self._pct(decode_ms, 0.95), 2),
        }
        if self._prefix is not None:
            # hit-rate / shared-page / COW / eviction gauges
            # (prefix_cache.PrefixCache.stats), serve_-prefixed into the
            # one counters schema /metrics and the timers export share
            for k, v in self._prefix.stats().items():
                out["serve_" + k] = v
        if self.spec_decode_k:
            out["serve_spec_rounds"] = self._spec_rounds
            out["serve_spec_proposed"] = self._spec_proposed
            out["serve_spec_accepted"] = self._spec_accepted
            out["serve_spec_accept_rate"] = round(
                self._spec_accepted / max(self._spec_proposed, 1), 4)
        if self.costs is not None:
            # device-cost gauges (ISSUE 15; ABSENT when the registry is
            # off so the legacy JSON schema stays byte-compatible):
            # aggregated per-request modeled work + pool occupancy-time,
            # and — when the chip is known — modeled roofline device
            # time vs measured round wall (the dispatch-overhead gauge)
            out["serve_modeled_gflops"] = round(self._modeled_gflops, 3)
            out["serve_page_rounds"] = self._page_rounds
            out["serve_cost_records"] = self.costs.captures
            if self.chip is not None:
                out["serve_chip_spec"] = self.chip.label()
            if self._modeled_device_ms > 0 and self._measured_round_ms > 0:
                out["serve_dispatch_overhead_pct"] = round(
                    (self._measured_round_ms - self._modeled_device_ms)
                    / self._measured_round_ms * 100, 2)
        if self.window is not None:
            # sliding-window gauges (ISSUE 19; gated like every other
            # feature group so the window-off JSON stays byte-
            # compatible): the configured window and the pages returned
            # to the pool mid-flight
            out["serve_window_size"] = self.window
            out["serve_window_reclaimed_pages"] = self._window_reclaimed
        if self._sentinel is not None:
            # gated like the cost gauges: the sentinel-off schema is
            # the legacy one
            out["serve_perf_regressions"] = self._sentinel.trips
            out["serve_perf_bad_rounds"] = self._sentinel.bad_total
        if (self._transfers_out or self._transfers_in):
            # cross-replica hand-off gauges (ISSUE 17): present only
            # once this engine has actually shipped/received pages, so
            # every non-disaggregated deployment keeps the legacy JSON
            out["serve_transfers_out"] = self._transfers_out
            out["serve_transfer_pages_out"] = self._transfer_pages_out
            out["serve_transfers_in"] = self._transfers_in
            out["serve_transfer_pages_in"] = self._transfer_pages_in
        return out

    def export_gauges(self, timers=None):
        timers = timers if timers is not None else self.timers
        if timers is None:
            return
        for name, value in self.counters().items():
            timers.gauge(name, value)

    def histograms(self):
        """The engine's latency histograms (telemetry/prometheus.py):
        TTFT, per-decode-token-advance round ms, queue wait — the
        distributional SLO metrics the point-percentile gauges in
        counters() cannot express."""
        return list(self._hists.values())

    def prometheus_metrics(self) -> str:
        """The Prometheus text exposition GET /metrics serves under
        content negotiation: every numeric counter as a gauge, string
        facts as one info metric, plus the real histograms — and, with
        the cost registry on, the per-(contract, specialization)
        compiled-cost gauges as labeled samples (ISSUE 15). The JSON
        path (counters()) stays byte-compatible and untouched."""
        text = render_prometheus(self.counters(), self.histograms())
        if self.costs is not None:
            lines = self.costs.prometheus_lines()
            if lines:
                text += "\n".join(lines) + "\n"
        return text

    def flight_record(self) -> dict:
        """On-demand flight-recorder snapshot (GET /flight_record):
        the same artifact a dying engine dumps, with live counters —
        and, with the cost registry on, the full compiled-cost table —
        attached."""
        self.recorder.note_counters(self.counters())
        return self.recorder.snapshot(
            reason="on-demand",
            extra={"costs": self.costs.snapshot()}
            if self.costs is not None else None)
