"""Prefix-affinity replica router: one front end over N decode-engine
replicas (ISSUE 14).

A single engine — even tp-sharded — caps out at one mesh's throughput;
the next scaling axis is N independent replicas behind a dispatcher.
The interesting routing decision is CACHE-AWARE: production traffic is
dominated by shared system prompts (the `extra.serving.prefix` bench
mix), and each replica's `PrefixCache` (inference/prefix_cache.py)
holds the shared pages of exactly the prompts IT has served. Random or
round-robin dispatch scatters a shared prefix across every replica —
each one pays the full prefill once and caches a private copy; routing
by prefix affinity sends a prompt to the replica that already holds its
longest page-aligned prefix, so the fleet prefills each shared prefix
roughly once and TTFT on shared traffic collapses toward the cache-hit
floor (bench `extra.serving.scaleout` measures affinity-vs-random p95
TTFT on the 80%-shared mix).

Design (each rule is load-bearing):

- **The router's index is ADVISORY, never authoritative.** It is a
  page-aligned prefix -> replica map maintained router-side from the
  router's own dispatch history (full pages only, capped at
  len(prompt) - 1 — exactly the prefixes a replica's PrefixCache can
  register). The replica's cache may have evicted an entry under pool
  pressure, a hash chain may collide, a replica may have restarted: a
  stale or wrong hit only routes a request to a colder replica that
  re-prefills — a perf miss, never a correctness hazard. That is what
  licenses the O(len(prompt)) rolling-hash walk instead of storing
  token tuples.
- **Health feeds routing, not the other way round.** Liveness comes
  from the replica's existing `/health` surface (`DecodeEngine.health`
  in process, GET /health over the wire): a poisoned serve loop
  (`broken`) or dead thread takes the replica out of rotation, its
  index entries drop (the pages died with its pools), and a cooldown
  re-probe brings a recovered replica back cold. A submit-time failure
  (engine stopped/poisoned mid-dispatch) marks the replica down and
  FAILS OVER to the next candidate in policy order; `QueueFull` on one
  replica tries the others before surfacing (the fleet is full only
  when every queue is).
- **Fallback is least-queue-depth.** On an affinity miss (or with
  `affinity=False`) the request goes to the healthy replica with the
  smallest queue_depth + slots_busy — the same load signal `/metrics`
  exports. `fallback="random"` (seeded) exists as the control arm the
  scaleout bench compares affinity against.
- **Drain on stop.** `stop(drain=True)` drains every replica's queue
  and slots before returning — the server's graceful-shutdown contract,
  fleet-wide.

The router deliberately duck-types the slice of the `DecodeEngine`
surface the HTTP layer uses (`submit`/`cancel`/`counters`/`health`/
`prometheus_metrics`/`flight_record`/`start`/`stop` + the
max_context/page_size/num_pages admission limits), so
`MegatronServer(engine=router)` serves a fleet through the same
handler code that serves one engine. Aggregation rules: additive
counters sum (`serve_kv_pool_bytes_fleet` scales each replica's
per-chip gauge by its tp), latency histograms merge by bucket (they
are cumulative by design — telemetry/prometheus.Histogram.merged) —
remote replicas' distributions included: HTTPReplica scrapes each
remote's Prometheus /metrics text and rebuilds its histograms via
`Histogram.from_cumulative` (ISSUE 15, closing the PR-14 gap where
the merged view covered in-process replicas only) — per-replica
detail rides under `"replicas"`, and `router_*` counters expose the
dispatch decisions themselves.

`EngineReplica` wraps an in-process engine (tests, bench emulation,
the `--router_replicas` serving tool); `HTTPReplica` speaks the same
protocol to a remote replica over its existing HTTP surface for
cross-host fleets (prompt keys are the request's token ids there too —
the router sits behind tokenization).
"""

from __future__ import annotations

import collections
import logging
import queue as queue_mod
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_logger = logging.getLogger(__name__)

__all__ = ["BacklogExceeded", "EngineReplica", "FleetUnavailable",
           "HTTPReplica", "PrefixAffinityIndex", "ReplicaRouter"]


def _queue_full_base():
    from megatron_llm_tpu.inference.engine import QueueFull

    return QueueFull


class FleetUnavailable(_queue_full_base()):
    """Every replica is poisoned/stopped/cooling down. Subclasses the
    engine's QueueFull ON PURPOSE: both mean "the fleet cannot take
    this request right now, retry later", and the HTTP layer already
    maps QueueFull to 503 + Retry-After — a bare RuntimeError would
    surface as a 500, which load balancers treat as a hard server
    fault and eject, exactly when the fleet is one cooldown away from
    recovering (GET /health reports the same transient state)."""


class BacklogExceeded(FleetUnavailable):
    """SLO-aware admission rejection (ISSUE 17): the MODELED drain time
    of every eligible replica's backlog exceeds the router's TTFT
    budget, so admitting would only manufacture a guaranteed SLO miss.
    A QueueFull by inheritance — the HTTP layer's existing 503 path —
    but the Retry-After it ships is the modeled drain estimate, not a
    constant: `retry_after_s` carries it."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class PrefixAffinityIndex:
    """Router-side page-aligned prefix -> replica map.

    Keys are a rolling hash chain over full prompt pages (key_d =
    hash((key_{d-1}, page_d's tokens))), so indexing and lookup walk a
    prompt ONCE — O(len(prompt)) — instead of hashing every
    page-aligned prefix tuple separately (O(P^2) tokens for a P-page
    prompt; the router sits on the submit path of every request).
    Hash collisions can alias two prefixes: acceptable by the advisory
    contract (a mis-route costs one cold prefill, never correctness).
    LRU-bounded: entries past `cap_entries` evict oldest-touched, the
    same pressure story as the replica-side cache it mirrors."""

    def __init__(self, page_size: int, cap_entries: int = 8192):
        assert page_size >= 1 and cap_entries >= 1
        self.page_size = page_size
        self.cap_entries = cap_entries
        # key -> replica id; OrderedDict move_to_end is the LRU touch
        self._map: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()

    def _keys(self, prompt: Sequence[int]):
        """The hash-chain keys of every full-page prefix of `prompt`,
        capped at len - 1 (mirroring PrefixCache: the last prompt token
        always forwards for its logits, so no replica can ever have
        cached through it)."""
        ps = self.page_size
        usable = (len(prompt) - 1) // ps
        key = 0x9E3779B9  # chain seed, any fixed value
        out = []
        for d in range(usable):
            key = hash((key, tuple(prompt[d * ps:(d + 1) * ps])))
            out.append(key)
        return out

    def lookup(self, prompt: Sequence[int]) -> Tuple[Optional[int], int]:
        """(replica holding the longest indexed prefix, pages matched);
        (None, 0) on a miss. Touches the winning entry's LRU stamp."""
        keys = self._keys(prompt)
        best, depth = None, 0
        for d, key in enumerate(keys, start=1):
            r = self._map.get(key)
            if r is None:
                break
            best, depth = r, d
        if best is not None:
            # re-touch the deepest hit only: it pins the chain
            self._map.move_to_end(keys[depth - 1])
        return best, depth

    def register(self, prompt: Sequence[int], replica: int) -> None:
        """Point every full-page prefix of `prompt` at `replica` — the
        replica's own PrefixCache will register the same pages as its
        prefill passes each boundary. Last writer wins (the newest
        holder is the warmest)."""
        for key in self._keys(prompt):
            self._map[key] = replica
            self._map.move_to_end(key)
        while len(self._map) > self.cap_entries:
            self._map.popitem(last=False)

    def drop_replica(self, replica: int) -> int:
        """Remove every entry pointing at `replica` (its pools — and
        with them every cached page — died with its serve loop).
        Returns the count dropped."""
        dead = [k for k, r in self._map.items() if r == replica]
        for k in dead:
            del self._map[k]
        return len(dead)

    def __len__(self) -> int:
        return len(self._map)


class EngineReplica:
    """In-process replica: a `DecodeEngine` (tagged with a replica_id)
    behind the replica protocol the router speaks. The serving tool's
    `--router_replicas`, the scaleout bench, and the router tests all
    use this form; cross-host fleets use HTTPReplica.

    `chaos` (ISSUE 20, inference/chaos.py) arms deterministic fault
    injection: submits eat injected latency and advance the kill
    trigger, the engine's per-round `_fault_hook` is installed (kills
    and sentinel-trip stalls fire INSIDE the scheduler round, through
    the real poison/telemetry paths), and exported hand-off payloads
    pass through the corruption hook. None (the default) leaves every
    path bitwise-untouched."""

    def __init__(self, engine, chaos=None):
        if engine.replica_id is None:
            raise ValueError(
                "a routed engine needs a replica_id (DecodeEngine("
                "replica_id=i)): the router routes cancel() by it and "
                "every metric/dump from the fleet must stay "
                "attributable")
        self.engine = engine
        self.replica_id = engine.replica_id
        self.chaos = chaos
        if chaos is not None:
            engine._fault_hook = chaos.engine_hook(engine.replica_id)

    # -- dispatch ----------------------------------------------------------

    def submit(self, prompt, tokens_to_generate, **kw):
        if self.chaos is not None:
            self.chaos.on_submit(self.replica_id)
        return self.engine.submit(prompt, tokens_to_generate, **kw)

    def cancel(self, req):
        self.engine.cancel(req)

    # -- cross-replica KV hand-off (ISSUE 17) ------------------------------

    def export_prefix(self, prompt):
        payload = self.engine.export_prefix(prompt)
        if self.chaos is not None:
            payload = self.chaos.on_export(self.replica_id, payload)
        return payload

    def import_prefix(self, payload):
        return self.engine.import_prefix(payload)

    # -- health / load (the /health + /metrics feed) -----------------------

    def health(self) -> dict:
        return self.engine.health()

    def load(self) -> int:
        h = self.engine.health()
        return h["queue_depth"] + h["slots_busy"]

    def modeled_backlog_flops(self):
        """The engine's modeled-FLOPs backlog (ISSUE 17) — None when
        its cost registry is off, and the router then falls back to
        the occupancy load() signal for the whole fleet."""
        return self.engine.modeled_backlog_flops()

    def modeled_backlog_s(self):
        return self.engine.modeled_backlog_seconds()

    def retry_after_s(self) -> float:
        return self.engine.retry_after_s()

    def counters(self) -> dict:
        return self.engine.counters()

    def fleet_kv_pool_bytes(self) -> int:
        """This replica's TOTAL pool HBM across its mesh: the per-chip
        gauge (the ISSUE 14 small-fix semantics) times serving_tp —
        what the router's fleet aggregate sums (summing per-chip
        numbers across tp>1 replicas would be neither per-chip nor
        fleet)."""
        return self.engine.kv_pool_bytes() * self.engine.serving_tp

    def histograms(self):
        return self.engine.histograms()

    def flight_record(self) -> dict:
        return self.engine.flight_record()

    def last_dump_path(self):
        """The engine's most recent flight-record artifact on disk
        (poison / sentinel-trip auto-dump), or None — what the router
        attaches to this replica's eviction event (ISSUE 20)."""
        return self.engine.recorder.last_dump_path

    # -- lifecycle ---------------------------------------------------------

    def warmup(self):
        """Pre-trace the engine's step executables — the replace cycle
        warms a replacement BEFORE rotating it in, so the first request
        it serves never eats a compile stall mid-recovery."""
        self.engine.warmup()

    def start(self):
        if self.engine._thread is None:
            self.engine.start()

    def stop(self, drain: bool = True):
        self.engine.stop(drain=drain)

    def drain(self):
        """Wait until the replica is idle: with the serve loop running,
        poll; otherwise step it here (manual-stepping tests/bench)."""
        eng = self.engine
        if eng._thread is not None and eng._thread.is_alive():
            while True:
                h = eng.health()
                if not h["alive"] or (h["queue_depth"] == 0
                                      and h["slots_busy"] == 0):
                    return
                time.sleep(0.002)
        eng.drain()

    @property
    def max_context(self) -> int:
        return self.engine.max_context

    @property
    def page_size(self) -> int:
        return self.engine.page_size

    @property
    def num_pages(self) -> int:
        return self.engine.num_pages


class HTTPReplica:
    """Remote replica over the engine server's existing HTTP surface
    (GET /health, GET /metrics, PUT /api). Generation submits ride a
    background thread per request so the router's submit stays
    non-blocking like the in-process form; the returned handle exposes
    the same `result(timeout)` contract as EngineRequest. Latency
    histograms ARE proxied (ISSUE 15): the probe also scrapes the
    replica's Prometheus text exposition (`/metrics?format=prometheus`)
    and rebuilds its cumulative histograms
    (telemetry/prometheus.histograms_from_prometheus), so the router's
    merged fleet distributions cover remote replicas too. Token
    streaming and cancel are still not proxied — front a remote
    fleet's streaming traffic at the replica, or run the router
    in-process with the engines (EngineReplica)."""

    def __init__(self, replica_id: int, base_url: str,
                 tokenizer=None, timeout_s: float = 600.0,
                 probe_ttl_s: float = 1.0,
                 probe_timeout_s: float = 5.0,
                 probe_backoff_cap_s: float = 30.0,
                 page_size: int = 64, max_context: int = 2048,
                 chaos=None):
        self.replica_id = replica_id
        self.base_url = base_url.rstrip("/")
        self.tokenizer = tokenizer
        self.timeout_s = timeout_s
        self.probe_ttl_s = probe_ttl_s
        self.page_size = page_size
        self.max_context = max_context
        self.num_pages = (max_context * 64) // page_size  # advisory
        # probe hardening (ISSUE 20 satellite): the probe's socket
        # timeout is a knob (was a hardcoded 5.0 — a sick host inside
        # a tighter SLO needs a tighter probe), and consecutive probe
        # FAILURES back the re-probe off exponentially (probe_ttl_s,
        # 2x, 4x ... capped at probe_backoff_cap_s) instead of hammering
        # a flapping replica at full rate; one success resets it. The
        # current backoff rides the router_reprobe_backoff_s gauge.
        self.probe_timeout_s = probe_timeout_s
        self.probe_backoff_cap_s = probe_backoff_cap_s
        self.chaos = chaos
        self._fail_streak = 0
        self._backoff_s = 0.0
        self._probe: Tuple[float, dict] = (0.0, {})
        # histogram scrape cached SEPARATELY from the health/load
        # probe: the probe feeds the ROUTING path (submit-time
        # health/load), which must never wait on the Prometheus text
        # fetch only the fleet /metrics aggregation consumes
        self._hist_probe: Tuple[float, list] = (0.0, [])

    def _get_raw(self, path: str, accept: Optional[str] = None,
                 timeout: Optional[float] = None) -> bytes:
        import urllib.request

        req = urllib.request.Request(
            self.base_url + path,
            headers={"Accept": accept} if accept else {})
        with urllib.request.urlopen(
                req, timeout=self.probe_timeout_s
                if timeout is None else timeout) as resp:
            return resp.read()

    def _get_json(self, path: str) -> dict:
        import json

        return json.loads(self._get_raw(path).decode())

    def _probed(self) -> dict:
        now = time.monotonic()
        t, snap = self._probe
        # a failing replica's snapshot lives probe_ttl_s PLUS the
        # current exponential backoff — a flapping remote re-probes at
        # a decaying rate, not the full routing rate
        if now - t < self.probe_ttl_s + self._backoff_s:
            return snap
        try:
            if self.chaos is not None \
                    and self.chaos.on_probe(self.replica_id):
                raise ConnectionError("chaos: health probe dropped")
            h = self._get_json("/health")
            m = self._get_json("/metrics")
            snap = {"health": h, "metrics": m}
            self._fail_streak = 0
            self._backoff_s = 0.0
        except Exception as e:  # noqa: BLE001 — a dead probe IS the signal
            snap = {"health": {"status": "unhealthy",
                               "engine": {"alive": False,
                                          "broken": repr(e),
                                          "queue_depth": 0,
                                          "slots_busy": 0}},
                    "metrics": {}}
            self._fail_streak += 1
            self._backoff_s = min(
                self.probe_ttl_s * (2 ** (self._fail_streak - 1)),
                self.probe_backoff_cap_s)
        self._probe = (now, snap)
        return snap

    def reprobe_backoff_s(self) -> float:
        """The current probe backoff (0.0 while the last probe
        succeeded) — the router's router_reprobe_backoff_s gauge takes
        the fleet max of these."""
        return self._backoff_s

    def _scrape_histograms(self) -> list:
        """The remote's latency distributions, rebuilt from its
        Prometheus text exposition, under its own TTL cache — lazy:
        only the fleet /metrics aggregation path (histograms()) pays
        this fetch, never a routing-time health/load probe. Failures
        degrade to [] — a replica on a pre-Prometheus build (or
        mid-restart) drops out of the merged distributions rather than
        failing the fleet scrape; its health/liveness probing is
        unaffected."""
        from megatron_llm_tpu.telemetry import histograms_from_prometheus

        now = time.monotonic()
        t, cached = self._hist_probe
        if now - t < self.probe_ttl_s:
            return cached
        try:
            text = self._get_raw("/metrics?format=prometheus",
                                 accept="text/plain").decode()
            hs = histograms_from_prometheus(text)
        except Exception as e:  # noqa: BLE001
            _logger.warning(
                "HTTPReplica %d: Prometheus histogram scrape failed "
                "(%r) — this replica's distributions are missing from "
                "the merged fleet /metrics this probe window",
                self.replica_id, e)
            hs = []
        self._hist_probe = (now, hs)
        return hs

    def health(self) -> dict:
        h = self._probed()["health"]
        eng = h.get("engine") or {}
        return {"alive": h.get("status") == "ok"
                and bool(eng.get("alive", True)),
                "broken": eng.get("broken"),
                "queue_depth": eng.get("queue_depth", 0),
                "slots_busy": eng.get("slots_busy", 0)}

    def load(self) -> int:
        h = self.health()
        return h["queue_depth"] + h["slots_busy"]

    def counters(self) -> dict:
        return dict(self._probed()["metrics"])

    def fleet_kv_pool_bytes(self) -> int:
        """The remote per-chip gauge as-is: a remote replica's tp is
        not visible over /metrics JSON, so a tp>1 REMOTE replica's
        contribution to the fleet sum undercounts by its tp — scrape
        the replica directly for exact sizing (its own counters are
        per-chip by contract)."""
        return int(self.counters().get("serve_kv_pool_bytes", 0))

    def histograms(self):
        """The remote's histograms, scraped from its Prometheus
        exposition on demand (rebuilt cumulative-bucket form —
        mergeable with the in-process replicas' via
        Histogram.merged)."""
        return list(self._scrape_histograms())

    def flight_record(self) -> dict:
        try:
            return self._get_json("/flight_record")
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)}

    def submit(self, prompt, tokens_to_generate, **kw):
        import json
        import urllib.request

        if self.tokenizer is None:
            raise ValueError(
                "HTTPReplica.submit needs a tokenizer to detokenize "
                "the prompt ids for PUT /api")
        payload = {
            "prompts": [self.tokenizer.detokenize(list(prompt))],
            "tokens_to_generate": int(tokens_to_generate),
            "top_k": int(kw.get("top_k", 1)),
            "top_p": float(kw.get("top_p", 0.0)),
            "temperature": float(kw.get("temperature", 1.0)),
        }
        if kw.get("seed", None) is not None:
            payload["random_seed"] = int(kw["seed"])

        handle = _HTTPResult(self.replica_id)

        def run():
            try:
                req = urllib.request.Request(
                    self.base_url + "/api",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="PUT")
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    handle._payload = json.loads(resp.read().decode())
            except Exception as e:  # noqa: BLE001 — surfaced at result()
                handle.error = repr(e)
            handle.done.set()

        threading.Thread(target=run, daemon=True).start()
        return handle

    def cancel(self, req):
        _logger.warning("HTTPReplica cannot cancel a remote request")

    # -- ISSUE 17 surfaces: not proxied over the wire ----------------------
    # A remote replica's modeled backlog and page pools are not
    # reachable through PUT /api; the router treats None/None/False as
    # "fall back to occupancy load / direct dispatch", so a mixed
    # fleet degrades to PR-14 behaviour instead of failing.

    def modeled_backlog_flops(self):
        return None

    def modeled_backlog_s(self):
        return None

    def retry_after_s(self):
        return None

    def export_prefix(self, prompt):
        return None

    def import_prefix(self, payload):
        return False

    def start(self):
        pass

    def stop(self, drain: bool = True):
        pass

    def drain(self):
        while self.load() > 0:
            time.sleep(0.05)


class _HTTPResult:
    """EngineRequest-shaped handle for one HTTPReplica submit."""

    def __init__(self, replica_id: int):
        self.replica_id = replica_id
        self.rid = -1
        self.done = threading.Event()
        self.error: Optional[str] = None
        self._payload: Optional[dict] = None

    def result(self, timeout: Optional[float] = None):
        if not self.done.wait(timeout):
            raise TimeoutError("remote request still running")
        if self.error is not None:
            raise RuntimeError(self.error)
        return self._payload, None


class _HandoffRequest:
    """EngineRequest-shaped handle for one TWO-STAGE dispatch (prefill
    replica -> page transfer -> decode replica, ISSUE 17). The caller
    gets it back immediately; a router orchestration thread runs the
    stages and attaches the decode replica's real EngineRequest when
    the final submit lands. Timestamps are absolute perf_counter
    values like EngineRequest's, with `t_submit` stamped at ROUTER
    submit time — so TTFT measured on this handle honestly includes
    the prefill stage and the page transfer, not just the decode
    replica's queue wait."""

    def __init__(self, prompt, tokens_to_generate, stream: bool = False):
        self.prompt = list(prompt)
        self.tokens_to_generate = int(tokens_to_generate)
        self.rid = -1  # until attach: no engine has admitted it yet
        self.replica_id: Optional[int] = None
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.timed_out = False
        self.cancelled = False
        self.inner = None  # the decode replica's EngineRequest
        self.tokens: list = []
        self.log_probs: list = []
        self.return_log_probs = False
        self.stream_q = queue_mod.SimpleQueue() if stream else None
        self.t_submit = time.perf_counter()
        self.t_first = 0.0
        self.t_done = 0.0

    def attach(self, inner) -> None:
        self.inner = inner
        self.rid = getattr(inner, "rid", -1)
        self.replica_id = getattr(inner, "replica_id", None)

    def finalize(self, inner) -> None:
        """Mirror the finished inner request's outcome onto the handle
        the caller holds, then release waiters."""
        self.tokens = list(getattr(inner, "tokens", []) or [])
        self.log_probs = list(getattr(inner, "log_probs", []) or [])
        self.return_log_probs = bool(
            getattr(inner, "return_log_probs", False))
        self.error = getattr(inner, "error", None)
        self.timed_out = bool(getattr(inner, "timed_out", False))
        # t_first may already be stamped at prefill-stage completion
        # (greedy hand-off: the donor's 1-token run IS the first token
        # of the continuation — the decode replica regenerates it
        # bitwise-identically) — keep the earlier, truthful timestamp
        if not self.t_first:
            self.t_first = getattr(inner, "t_first", 0.0) or 0.0
        self.t_done = getattr(inner, "t_done", 0.0) or time.perf_counter()
        self.done.set()

    def fail(self, msg: str, timed_out: bool = False) -> None:
        self.error = msg
        self.timed_out = timed_out
        self.t_done = time.perf_counter()
        if self.stream_q is not None:
            self.stream_q.put(None)  # close any SSE consumer
        self.done.set()

    def result(self, timeout: Optional[float] = None):
        """EngineRequest.result contract: (tokens, log_probs), raising
        TimeoutError/RuntimeError exactly like a direct dispatch."""
        if not self.done.wait(timeout):
            raise TimeoutError("hand-off request still running")
        if self.error is not None:
            if self.timed_out:
                raise TimeoutError(self.error)
            raise RuntimeError(self.error)
        return self.tokens, (self.log_probs if self.return_log_probs
                             else None)


class _RecoverableRequest:
    """EngineRequest-shaped handle that survives its replica's death
    (ISSUE 20): the router hands it back instead of the engine's raw
    request when `recover_requests=True`. If the inner request fails
    with a replica-death error (serve loop poisoned, engine stopped,
    an injected chaos kill) BEFORE any token reached the caller, the
    proxy transparently resubmits the same request through the router
    — a fresh probe excludes the dead replica — up to `max_resubmits`
    times. Greedy decoding makes the retry bitwise: the replacement
    replica regenerates exactly the token stream the dead one would
    have produced (and sampled requests carry their per-request seed,
    so they replay identically too).

    What is NOT retried (each documented in docs/GUIDE.md
    "Self-driving fleet operations"):
    - PARTIALLY-STREAMED requests: tokens already left the building;
      a resubmit would re-deliver or reorder them mid-SSE-stream. The
      proxy fails LOUDLY (the error names the streamed count and tells
      the client to honour Retry-After) and closes the stream — it
      never hangs.
    - deadline-shed (`timed_out`) and cancelled requests: the caller
      already gave up; resurrecting its request would waste fleet
      capacity on an abandoned answer.
    - request-shaped errors (ValueError): every replica refuses them
      identically.

    Streaming requests pump through a relay thread (the proxy owns the
    caller-visible stream_q; each inner attempt gets its own), so the
    SSE layer's contract — every generated token, then one None
    sentinel — holds across a mid-flight replica swap. Non-streaming
    requests recover lazily inside result(): no thread, no cost until
    a replica actually dies."""

    # substrings that identify a REPLICA death (vs a request fault):
    # the serve-loop poison prefix, engine stop, submit-on-stopped,
    # and the chaos injector's kill tag
    _DEATH_MARKERS = ("engine step failed", "engine stopped",
                      "engine is stopped", "chaos:")

    def __init__(self, router, prompt, tokens_to_generate, kw, inner,
                 budget: int):
        self._router = router
        self._prompt = list(prompt)
        self._n = int(tokens_to_generate)
        self._kw = dict(kw)
        self._inner = inner
        self._budget = int(budget)
        self._t_submit0 = getattr(inner, "t_submit", 0.0)
        self.cancelled = False
        self.error: Optional[str] = None
        self.timed_out = False
        self.done = threading.Event()
        self._tokens: Optional[list] = None
        self._log_probs = None
        self._streamed = 0
        self.stream_q = None
        if kw.get("stream"):
            self.stream_q = queue_mod.SimpleQueue()
            threading.Thread(target=self._pump, daemon=True).start()

    # -- EngineRequest-shaped surface (SSE id:, router.cancel, bench) ------

    @property
    def rid(self):
        return getattr(self._inner, "rid", -1)

    @property
    def replica_id(self):
        return getattr(self._inner, "replica_id", None)

    @property
    def tokens(self):
        if self._tokens is not None:
            return self._tokens
        return getattr(self._inner, "tokens", [])

    @property
    def log_probs(self):
        return getattr(self._inner, "log_probs", [])

    @property
    def return_log_probs(self):
        return getattr(self._inner, "return_log_probs", False)

    @property
    def t_submit(self):
        # the ORIGINAL submit time survives resubmits: TTFT measured on
        # this handle honestly includes the death + recovery
        return self._t_submit0

    @property
    def t_first(self):
        return getattr(self._inner, "t_first", 0.0)

    @property
    def t_done(self):
        return getattr(self._inner, "t_done", 0.0)

    # -- recovery ----------------------------------------------------------

    def _recoverable(self, inner, err: str) -> bool:
        if self._budget <= 0 or self.cancelled:
            return False
        if getattr(inner, "timed_out", False) \
                or getattr(inner, "cancelled", False):
            return False
        return any(m in err for m in self._DEATH_MARKERS)

    def _resubmit(self):
        """One recovery attempt: redispatch through the router (the
        fresh probe sees the dead replica's broken health and routes
        around it). Raises whatever the redispatch raises — a fleet
        with no healthy replica surfaces as FleetUnavailable, the 503 +
        Retry-After shape."""
        self._budget -= 1
        req = self._router._dispatch_raw(self._prompt, self._n,
                                         dict(self._kw))
        with self._router._lock:
            self._router._resubmitted += 1
        _logger.warning(
            "router: request resubmitted to replica %s after replica "
            "death (%d retr%s left)", getattr(req, "replica_id", None),
            self._budget, "y" if self._budget == 1 else "ies")
        self._inner = req
        return req

    def result(self, timeout: Optional[float] = None):
        if self.stream_q is not None:
            # streaming: the pump thread owns recovery and the final
            # outcome — result() just reports it
            if not self.done.wait(timeout):
                raise TimeoutError("request still running")
            if self.error is not None:
                if self.timed_out:
                    raise TimeoutError(self.error)
                raise RuntimeError(self.error)
            return self._tokens, self._log_probs
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            inner = self._inner
            left = (None if deadline is None
                    else max(deadline - time.monotonic(), 0.0))
            try:
                out = inner.result(left)
            except TimeoutError:
                # either our wait budget ran out or the request was
                # deadline-shed — neither is retried
                self.timed_out = getattr(inner, "timed_out", False)
                self.error = getattr(inner, "error", None)
                raise
            except RuntimeError as e:
                if not self._recoverable(inner, str(e)):
                    self.error = str(e)
                    self.done.set()
                    raise
                self._resubmit()  # raises FleetUnavailable when the
                continue          # whole fleet is gone (a 503, not a hang)
            self._tokens, self._log_probs = out
            self.done.set()
            return out

    def _pump(self):
        """Streaming relay: forward each inner attempt's tokens onto
        the caller's stream; on a pre-stream replica death, resubmit
        and keep pumping; on any terminal outcome, mirror it and close
        the stream with the one None sentinel."""
        while True:
            inner = self._inner
            q = getattr(inner, "stream_q", None)
            while True:
                tok = q.get()  # the engine ALWAYS closes with None
                if tok is None:
                    break
                self._streamed += 1
                self.stream_q.put(tok)
            # sentinel seen: error/done were set before _finish()
            err = getattr(inner, "error", None)
            if err is None:
                self._tokens = list(getattr(inner, "tokens", []) or [])
                self._log_probs = (list(inner.log_probs)
                                   if getattr(inner, "return_log_probs",
                                              False) else None)
                self.done.set()
                self.stream_q.put(None)
                return
            if self._streamed == 0 and self._recoverable(inner, err):
                try:
                    self._resubmit()
                    continue
                except BaseException as e:  # noqa: BLE001 — surfaced
                    err = (f"resubmit after replica death failed: "
                           f"{e!r} (original death: {err})")
            elif self._streamed > 0 and any(
                    m in err for m in self._DEATH_MARKERS):
                err = (f"replica died after {self._streamed} token(s) "
                       f"already streamed: {err} — partially-streamed "
                       f"requests are never resubmitted (a retry would "
                       f"re-deliver tokens the client already has); "
                       f"stream closed, retry the request after the "
                       f"Retry-After interval")
            self.error = err
            self.timed_out = getattr(inner, "timed_out", False)
            self.done.set()
            self.stream_q.put(None)
            return


class ReplicaRouter:
    """Prefix-affinity dispatcher over N replicas (module docstring).

    Knobs (docs/GUIDE.md "Serving on a tp mesh & replica routing"):
    - `affinity` (default True): route by the page-aligned prefix ->
      replica index; off, every dispatch takes the fallback policy
      (the scaleout bench's control arm).
    - `fallback` ("least_loaded" | "random"): the policy on an
      affinity miss / affinity off. Least-loaded reads
      queue_depth + slots_busy from the replica's health surface.
    - `index_entries`: LRU bound of the affinity index.
    - `unhealthy_cooldown_s`: how long a replica marked down at
      submit time stays out of rotation before the next health
      re-probe may readmit it.

    Disaggregated two-stage mode (ISSUE 17, docs/GUIDE.md
    "Disaggregated serving"): pass `prefill_replicas=` +
    `decode_replicas=` INSTEAD of `replicas=`. Long prompts (>=
    `disagg_min_prompt_pages` full pages) prefill on the
    least-modeled-backlog prefill replica, their finished KV pages
    ship to the least-backlogged decode replica
    (export_prefix/import_prefix), and the full request then admits
    there as a prefix HIT — decode replicas never eat long mixed
    rounds. Short prompts take the direct path onto decode replicas
    unchanged. `ttft_slo_s` arms modeled-backlog admission: when every
    eligible replica's modeled drain time exceeds the budget, submit
    raises BacklogExceeded (a 503) carrying the modeled Retry-After.
    """

    def __init__(self, replicas: Optional[List] = None, *,
                 affinity: bool = True,
                 fallback: str = "least_loaded",
                 index_entries: int = 8192,
                 unhealthy_cooldown_s: float = 1.0,
                 rng_seed: int = 0,
                 prefill_replicas: Optional[List] = None,
                 decode_replicas: Optional[List] = None,
                 disagg_min_prompt_pages: int = 2,
                 ttft_slo_s: Optional[float] = None,
                 handoff_timeout_s: float = 600.0,
                 recover_requests: bool = False,
                 max_resubmits: int = 2):
        if (prefill_replicas is None) != (decode_replicas is None):
            raise ValueError(
                "disaggregated mode takes BOTH prefill_replicas= and "
                "decode_replicas= (a fleet with only one role cannot "
                "hand pages off)")
        self.disagg = prefill_replicas is not None
        if self.disagg:
            if replicas:
                raise ValueError(
                    "pass either replicas= (symmetric fleet) or the "
                    "prefill_replicas=/decode_replicas= pair, not both")
            if not prefill_replicas or not decode_replicas:
                raise ValueError(
                    "disaggregated mode needs at least one prefill AND "
                    "one decode replica")
            self._prefill_ids = [r.replica_id for r in prefill_replicas]
            self._decode_ids = [r.replica_id for r in decode_replicas]
            replicas = list(prefill_replicas) + list(decode_replicas)
        else:
            replicas = list(replicas or [])
            self._prefill_ids = []
            self._decode_ids = [r.replica_id for r in replicas]
        if not replicas:
            raise ValueError("a router needs at least one replica")
        if fallback not in ("least_loaded", "random"):
            raise ValueError(f"unknown fallback policy {fallback!r}")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        sizes = {r.page_size for r in replicas}
        if len(sizes) != 1:
            raise ValueError(
                f"replicas disagree on page_size ({sorted(sizes)}): "
                f"the affinity index is page-aligned and needs ONE "
                f"granularity")
        self.replicas = list(replicas)
        self._by_id: Dict[int, object] = {r.replica_id: r
                                          for r in replicas}
        self.affinity = affinity
        self.fallback = fallback
        self.page_size = sizes.pop()
        self.max_context = min(r.max_context for r in replicas)
        self.num_pages = min(r.num_pages for r in replicas)
        self._index = PrefixAffinityIndex(self.page_size, index_entries)
        self._rng = random.Random(rng_seed)
        self.unhealthy_cooldown_s = unhealthy_cooldown_s
        self.disagg_min_prompt_pages = max(int(disagg_min_prompt_pages), 1)
        self.ttft_slo_s = ttft_slo_s
        self.handoff_timeout_s = handoff_timeout_s
        self._down_until: Dict[int, float] = {}  # replica_id -> monotonic
        self._lock = threading.Lock()  # index + policy state (submit
        # can be called from N HTTP handler threads concurrently)
        self._thread = None  # duck-typed "started" flag (server.run)

        # dispatch accounting (served under counters()["router"])
        self._dispatches = 0
        self._affinity_hits = 0
        self._affinity_hit_pages = 0
        self._failovers = 0
        self._rejected = 0
        self._per_replica: Dict[int, int] = {r.replica_id: 0
                                             for r in replicas}
        # ISSUE 17 accounting — exported GATED on disagg/SLO mode so
        # the symmetric fleet's /metrics JSON stays byte-compatible
        self._prefill_dispatches = 0
        self._transfer_pages = 0
        self._transfer_ms = 0.0
        self._slo_rejected = 0
        # placement-decision trail (reproducibility: every routing
        # choice alongside the modeled backlogs it was made from)
        self._decisions: collections.deque = collections.deque(
            maxlen=256)
        # ISSUE 20: in-flight recovery + self-driving fleet state.
        # `recover_requests` wraps every direct-path handle in a
        # _RecoverableRequest; everything below is gated on it (or on
        # a FleetController registering via _managed) so the
        # unmanaged router's /metrics and flight_record stay
        # byte-identical to the legacy schema.
        self.recover_requests = bool(recover_requests)
        self.max_resubmits = int(max_resubmits)
        self._resubmitted = 0
        self._fleet_replaced = 0
        self._scale_events = 0
        self._handoff_rejected = 0
        self._managed = False      # a FleetController owns this fleet
        self._controller = None
        # eviction trail: every replica that left rotation, with the
        # flight-record dump it left behind (ROADMAP 5a correlation)
        self._evictions: collections.deque = collections.deque(
            maxlen=64)

    # -- health ------------------------------------------------------------

    def _probe(self) -> Tuple[List[int], Dict[int, int], Dict[int, float]]:
        """(healthy replica ids, occupancy loads, modeled-FLOPs
        backlogs). Runs OUTSIDE the router lock on purpose: for
        HTTPReplica fleets health/load are network probes (seconds of
        blocking I/O on a sick host), and one hung replica must never
        stall every other handler thread's submit behind the lock.
        `_down_until` reads here are unsynchronized — a stale read only
        delays rotation changes by one dispatch, which the advisory
        contract absorbs. The modeled backlog (ISSUE 17) is absent for
        replicas without a cost registry (and for remote replicas);
        ordering only trusts it when EVERY candidate reports one."""
        now = time.monotonic()
        healthy: List[int] = []
        loads: Dict[int, int] = {}
        mloads: Dict[int, float] = {}
        for rep in self.replicas:
            rid = rep.replica_id
            if self._down_until.get(rid, 0.0) > now:
                continue
            h = rep.health()
            if h["alive"] and h["broken"] is None:
                healthy.append(rid)
                loads[rid] = h["queue_depth"] + h["slots_busy"]
                fn = getattr(rep, "modeled_backlog_flops", None)
                if fn is not None:
                    try:
                        m = fn()
                    except Exception:  # noqa: BLE001 — advisory signal
                        m = None
                    if m is not None:
                        mloads[rid] = float(m)
            else:
                self._mark_down(rid, h["broken"] or "serve loop dead")
        return healthy, loads, mloads

    def _mark_down(self, rid: int, why,
                   cooldown: Optional[float] = None) -> None:
        """Takes the router lock itself — callers must NOT hold it.
        Every departure appends an eviction event carrying the
        replica's last flight-record dump path (ISSUE 20 / ROADMAP 5a:
        poison rotation and the engine's auto-dump used to be
        uncorrelated artifacts)."""
        rep = self._by_id.get(rid)
        dump = None
        if rep is not None:
            fn = getattr(rep, "last_dump_path", None)
            if fn is not None:
                try:
                    dump = fn()
                except Exception:  # noqa: BLE001 — advisory attach
                    dump = None
        cd = self.unhealthy_cooldown_s if cooldown is None else cooldown
        with self._lock:
            dropped = self._index.drop_replica(rid)
            self._down_until[rid] = time.monotonic() + cd
            self._evictions.append({
                "t": time.time(), "replica": rid,
                "why": str(why)[:200], "index_dropped": dropped,
                "flight_dump": dump})
        _logger.warning(
            "router: replica %d out of rotation (%s); %d affinity "
            "entries dropped (its pools died with it), re-probe in "
            "%.1fs%s", rid, why, dropped, cd,
            f", flight record at {dump}" if dump else "")

    # -- fleet mutation (ISSUE 20: the FleetController's surface) ----------

    def condemn(self, rid: int, why: str = "condemned") -> None:
        """Take a replica out of rotation PERMANENTLY (infinite
        cooldown): the health re-probe can never readmit it. The
        controller's replace cycle condemns first — stopping admission
        — then drains, stops, and swaps in the replacement via
        replace_replica() (which clears the condemnation)."""
        self._mark_down(rid, why, cooldown=float("inf"))

    def replace_replica(self, rid: int, new_rep) -> None:
        """Swap a (condemned/dead) replica for a warmed replacement
        carrying the SAME replica id — the rotation-back-in step of
        the replace cycle. The replacement's pools start empty, so its
        affinity-index entries (already dropped at condemn time) stay
        dropped."""
        if new_rep.replica_id != rid:
            raise ValueError(
                f"replacement carries replica_id "
                f"{new_rep.replica_id}, expected {rid} — the fleet's "
                f"id space (dispatch accounting, SSE replica tags) "
                f"must stay stable across a replace")
        if new_rep.page_size != self.page_size:
            raise ValueError(
                f"replacement page_size {new_rep.page_size} != fleet "
                f"page_size {self.page_size}")
        with self._lock:
            if rid not in self._by_id:
                raise KeyError(f"no replica {rid} in this fleet")
            # rebuild as a NEW list: _probe/counters/health iterate
            # self.replicas unlocked, and must see either the old or
            # the new fleet, never a half-mutated one
            self.replicas = [new_rep if r.replica_id == rid else r
                             for r in self.replicas]
            self._by_id[rid] = new_rep
            self._index.drop_replica(rid)
            self._down_until.pop(rid, None)  # lift the condemnation
        _logger.warning("router: replica %d replaced, back in "
                        "rotation", rid)

    def add_replica(self, rep) -> None:
        """Grow the active set (scale-up). Symmetric fleets only: a
        disaggregated fleet's role lists are a topology decision the
        controller does not make."""
        if self.disagg:
            raise ValueError("add_replica: disaggregated fleets do "
                             "not support elastic scaling")
        if rep.page_size != self.page_size:
            raise ValueError(
                f"new replica page_size {rep.page_size} != fleet "
                f"page_size {self.page_size}")
        with self._lock:
            if rep.replica_id in self._by_id:
                raise ValueError(
                    f"duplicate replica id {rep.replica_id}")
            self.replicas = self.replicas + [rep]
            self._by_id[rep.replica_id] = rep
            self._decode_ids = self._decode_ids + [rep.replica_id]
            self._per_replica.setdefault(rep.replica_id, 0)
            self.max_context = min(r.max_context for r in self.replicas)
            self.num_pages = min(r.num_pages for r in self.replicas)
        _logger.warning("router: replica %d added (fleet now %d)",
                        rep.replica_id, len(self.replicas))

    def remove_replica(self, rid: int):
        """Shrink the active set (scale-down): drop the replica from
        rotation and RETURN it — the caller owns the drain + stop (the
        controller drains it outside the router lock). Refuses to
        remove the last replica: an empty fleet cannot 503 its way
        back."""
        if self.disagg:
            raise ValueError("remove_replica: disaggregated fleets do "
                             "not support elastic scaling")
        with self._lock:
            if rid not in self._by_id:
                raise KeyError(f"no replica {rid} in this fleet")
            if len(self.replicas) <= 1:
                raise ValueError("remove_replica: refusing to remove "
                                 "the last replica")
            rep = self._by_id.pop(rid)
            self.replicas = [r for r in self.replicas
                             if r.replica_id != rid]
            self._decode_ids = [r for r in self._decode_ids
                                if r != rid]
            self._index.drop_replica(rid)
            self._down_until.pop(rid, None)
            self.max_context = min(r.max_context for r in self.replicas)
            self.num_pages = min(r.num_pages for r in self.replicas)
        _logger.warning("router: replica %d removed (fleet now %d)",
                        rid, len(self.replicas))
        return rep

    def note_replaced(self) -> None:
        with self._lock:
            self._fleet_replaced += 1

    def note_scale_event(self) -> None:
        with self._lock:
            self._scale_events += 1

    def evictions(self) -> list:
        """The bounded eviction trail (replica departures with their
        flight-record dump paths)."""
        with self._lock:
            return [dict(e) for e in self._evictions]

    # -- dispatch ----------------------------------------------------------

    @staticmethod
    def _order_by_backlog(ids: List[int], loads: Dict[int, int],
                          mloads: Dict[int, float]) -> List[int]:
        """Least-backlogged-first ordering: by modeled FLOPs when EVERY
        candidate reports them (a 4k-token prefill then outweighs ten
        12-token completions, which raw occupancy cannot see), by
        queue_depth + slots_busy otherwise — mixing modeled and
        occupancy numbers would compare incommensurable units."""
        if ids and all(rid in mloads for rid in ids):
            return sorted(ids, key=lambda rid: (mloads[rid], rid))
        return sorted(ids, key=lambda rid: (loads.get(rid, 0), rid))

    def _pick(self, prompt, healthy: List[int], loads: Dict[int, int],
              mloads: Dict[int, float]) -> List[int]:
        """Candidate replica ids in dispatch order: affinity hit first
        (when it is healthy), then the fallback-policy ordering of the
        rest — the failover path walks this list. Called under the
        router lock (index + counters); load comes pre-probed."""
        order: List[int] = []
        if self.affinity:
            hit, pages = self._index.lookup(prompt)
            if hit is not None and hit in healthy:
                order.append(hit)
                self._affinity_hits += 1
                self._affinity_hit_pages += pages
        rest = [r for r in healthy if r not in order]
        if self.fallback == "random":
            self._rng.shuffle(rest)
        else:
            rest = self._order_by_backlog(rest, loads, mloads)
        return order + rest

    def _admission_gate(self, cands: List[int]) -> None:
        """SLO-aware admission (ISSUE 17): with `ttft_slo_s` set,
        reject when the MODELED drain time of every eligible replica
        exceeds the budget — the request would be born an SLO miss.
        Stays open when any candidate cannot model its backlog (no
        cost registry / no chip spec / remote): an occupancy number is
        not a drain time, and rejecting on a guess would be the
        dishonest Retry-After this satellite exists to remove."""
        if self.ttft_slo_s is None:
            return
        secs: List[float] = []
        for rid in cands:
            fn = getattr(self._by_id[rid], "modeled_backlog_s", None)
            s = None
            if fn is not None:
                try:
                    s = fn()
                except Exception:  # noqa: BLE001 — advisory signal
                    s = None
            if s is None:
                return
            secs.append(float(s))
        if not secs or min(secs) <= self.ttft_slo_s:
            return
        best = min(secs)
        retry = float(min(max(best, 1.0), 60.0))
        with self._lock:
            self._rejected += 1
            self._slo_rejected += 1
            self._decisions.append({
                "path": "slo_reject", "modeled_backlog_s": round(best, 4),
                "ttft_slo_s": self.ttft_slo_s,
                "retry_after_s": retry})
        raise BacklogExceeded(
            f"router: modeled backlog {best:.2f}s exceeds the "
            f"ttft_slo_s={self.ttft_slo_s}s budget on every eligible "
            f"replica — admitting now would guarantee an SLO miss; "
            f"retry in {retry:.0f}s", retry_after_s=retry)

    def submit(self, prompt, tokens_to_generate, **kw):
        """Dispatch one request; the returned handle is the chosen
        engine's own EngineRequest (rid + replica_id identify it
        fleet-wide) — or, on the disaggregated two-stage path, a
        _HandoffRequest proxy with the same result()/stream contract.
        Raises the last replica error — QueueFull only when EVERY
        healthy replica's queue is full, FleetUnavailable (a QueueFull:
        the HTTP layer's 503 + Retry-After) when no replica is healthy
        at all, BacklogExceeded when modeled admission rejects.

        With `recover_requests=True` (symmetric fleets) the returned
        handle is a _RecoverableRequest: if its replica dies before any
        token streamed, the handle transparently redispatches through
        this router (ISSUE 20 in-flight recovery)."""
        if not self.disagg:
            prompt = list(prompt)
            req = self._dispatch_raw(prompt, tokens_to_generate, kw)
            if self.recover_requests:
                return _RecoverableRequest(self, prompt,
                                           tokens_to_generate, kw, req,
                                           self.max_resubmits)
            return req
        healthy, loads, mloads = self._probe()  # blocking I/O unlocked
        if not healthy:
            with self._lock:
                self._rejected += 1
            raise FleetUnavailable(
                "router: no healthy replica (all poisoned/stopped "
                "or cooling down) — the fleet cannot take traffic; "
                "retry after the cooldown")
        prompt = list(prompt)
        pre = [r for r in self._prefill_ids if r in healthy]
        # short prompts stay on decode replicas; with every decode
        # replica down the fleet degrades to whatever is healthy
        # (a prefill replica is a full engine) rather than 503ing
        dec = [r for r in self._decode_ids if r in healthy] or healthy
        self._admission_gate(dec)
        pages = (len(prompt) - 1) // self.page_size
        if (pre and pages >= self.disagg_min_prompt_pages
                and not kw.get("return_log_probs")):
            # return_log_probs stays direct: a transferred-prefix HIT
            # skips those positions' logits entirely, and the two-stage
            # win is TTFT on long-prompt GENERATION traffic
            return self._submit_two_stage(prompt, tokens_to_generate,
                                          kw)
        return self._submit_direct(prompt, tokens_to_generate, kw,
                                   dec, loads, mloads)

    def _dispatch_raw(self, prompt, tokens_to_generate, kw):
        """One symmetric-fleet dispatch attempt: probe, admission
        gate, direct submit. Split out of submit() so the recovery
        proxy can redispatch a dead replica's request through a FRESH
        probe (which sees the death and routes around it)."""
        healthy, loads, mloads = self._probe()  # blocking I/O unlocked
        if not healthy:
            with self._lock:
                self._rejected += 1
            raise FleetUnavailable(
                "router: no healthy replica (all poisoned/stopped "
                "or cooling down) — the fleet cannot take traffic; "
                "retry after the cooldown")
        self._admission_gate(healthy)
        return self._submit_direct(prompt, tokens_to_generate, kw,
                                   healthy, loads, mloads)

    def _submit_direct(self, prompt, tokens_to_generate, kw,
                       cands: List[int], loads, mloads):
        from megatron_llm_tpu.inference.engine import QueueFull

        with self._lock:
            order = self._pick(prompt, cands, loads, mloads)
            self._dispatches += 1
        last_err: Optional[BaseException] = None
        for i, rid in enumerate(order):
            rep = self._by_id[rid]
            try:
                req = rep.submit(prompt, tokens_to_generate, **kw)
            except QueueFull as e:
                # this replica is full, the next may not be
                last_err = e
                with self._lock:
                    self._failovers += 1 if i + 1 < len(order) else 0
                continue
            except ValueError:
                # request-shaped error (oversize prompt etc.): every
                # replica would refuse it identically — surface as-is
                raise
            except Exception as e:  # noqa: BLE001 — poisoned mid-dispatch
                last_err = e
                self._mark_down(rid, repr(e))
                with self._lock:
                    self._failovers += 1 if i + 1 < len(order) else 0
                continue
            with self._lock:
                self._per_replica[rid] += 1
                if self.affinity:
                    self._index.register(prompt, rid)
                if self.disagg or self.ttft_slo_s is not None:
                    self._decisions.append({
                        "path": "direct", "replica": rid,
                        "prompt_tokens": len(prompt),
                        "loads": dict(loads),
                        "modeled_flops": dict(mloads)})
            return req
        with self._lock:
            self._rejected += 1
        raise last_err if last_err is not None else RuntimeError(
            "router: dispatch failed with no replica error")

    # -- two-stage (prefill -> transfer -> decode) dispatch ----------------

    def _submit_two_stage(self, prompt, tokens_to_generate, kw):
        proxy = _HandoffRequest(prompt, tokens_to_generate,
                                stream=bool(kw.get("stream")))
        with self._lock:
            self._dispatches += 1
        threading.Thread(
            target=self._run_two_stage,
            args=(proxy, prompt, tokens_to_generate, dict(kw)),
            daemon=True).start()
        return proxy

    def _run_two_stage(self, proxy, prompt, tokens_to_generate, kw):
        try:
            self._two_stage_inner(proxy, prompt, tokens_to_generate, kw)
        except BaseException as e:  # noqa: BLE001 — the caller holds
            # only the proxy; an unreported stage failure would hang it
            proxy.fail(f"two-stage dispatch failed: {e!r}",
                       timed_out=isinstance(e, TimeoutError))

    def _two_stage_inner(self, proxy, prompt, tokens_to_generate, kw):
        from megatron_llm_tpu.inference.engine import QueueFull

        # stage 1: full-prompt chunked prefill on the least-backlogged
        # prefill replica. A greedy 1-token run prefills the whole
        # prompt and registers its full pages on the donor's
        # PrefixCache; the single generated token never lands in a
        # registered page, so the export is exactly the prompt's
        # full-page prefix.
        healthy, loads, mloads = self._probe()
        payload, pre_rid = None, None
        t_x0 = None
        pre_ids = [r for r in self._prefill_ids if r in healthy]
        if pre_ids and not proxy.cancelled:
            pre_rid = self._order_by_backlog(pre_ids, loads, mloads)[0]
            pre = self._by_id[pre_rid]
            try:
                pre_req = pre.submit(
                    prompt, 1, top_k=1, seed=0,
                    use_eod_for_early_termination=False,
                    deadline_s=kw.get("deadline_s"))
                pre_req.result(timeout=self.handoff_timeout_s)
                t_x0 = time.perf_counter()
                payload = pre.export_prefix(prompt)
            except Exception as e:  # noqa: BLE001 — donor trouble
                # never fails the request: fall back to direct prefill
                # on the decode replica (the symmetric-path behaviour)
                if not isinstance(e, (QueueFull, TimeoutError)):
                    self._mark_down(pre_rid, repr(e))
                payload, t_x0 = None, None
            else:
                with self._lock:
                    self._prefill_dispatches += 1
                    self._per_replica[pre_rid] += 1
                # for a greedy request the donor's 1-token run already
                # produced the continuation's first token (the decode
                # replica regenerates it bitwise-identically off the
                # transferred pages), so TTFT is prefill-stage
                # completion — stamp it now, before splice + resubmit
                if kw.get("top_k") == 1:
                    proxy.t_first = getattr(pre_req, "t_first", 0.0) or 0.0

        # stage 2 + 3: splice the pages into the least-backlogged
        # decode replica, then submit the full request there — the
        # transferred chain admits as a prefix HIT, so the decode
        # replica prefills nothing (or, on fallback, everything: the
        # request is correct either way, only slower).
        healthy, loads, mloads = self._probe()
        dec_ids = [r for r in self._decode_ids if r in healthy] or healthy
        if not dec_ids:
            with self._lock:
                self._rejected += 1
            raise FleetUnavailable(
                "router: no decode replica healthy for the hand-off")
        order = self._order_by_backlog(dec_ids, loads, mloads)
        last_err: Optional[BaseException] = None
        for i, rid in enumerate(order):
            rep = self._by_id[rid]
            moved = 0
            try:
                if payload is not None and not proxy.cancelled:
                    try:
                        res = rep.import_prefix(payload)
                    except ValueError as e:
                        # corrupt/mismatched payload (ISSUE 20 chaos
                        # matrix): the receiver's geometry gate refused
                        # the splice. Degrade, don't fail — drop the
                        # payload and let the decode replica prefill
                        # the prompt itself (correct, only slower).
                        _logger.warning(
                            "router: decode replica %d rejected the "
                            "hand-off payload (%s) — degrading to a "
                            "local prefill", rid, e)
                        with self._lock:
                            self._handoff_rejected += 1
                        res, payload = False, None
                    if res:
                        moved = int(res.get("pages", 0))
                req = rep.submit(prompt, tokens_to_generate, **kw)
            except QueueFull as e:
                last_err = e
                with self._lock:
                    self._failovers += 1 if i + 1 < len(order) else 0
                continue
            except ValueError:
                raise
            except Exception as e:  # noqa: BLE001 — replica died
                # mid-transfer: mark it down and fail over. The donor
                # needs NO cleanup — its pages stayed registered and
                # unreferenced, reclaimable by its own LRU eviction.
                last_err = e
                self._mark_down(rid, repr(e))
                with self._lock:
                    self._failovers += 1 if i + 1 < len(order) else 0
                continue
            xfer_ms = (0.0 if t_x0 is None
                       else (time.perf_counter() - t_x0) * 1e3)
            with self._lock:
                self._per_replica[rid] += 1
                if self.affinity:
                    # future same-prefix prompts route straight to the
                    # replica now holding the transferred pages
                    self._index.register(prompt, rid)
                if moved:
                    self._transfer_pages += moved
                    self._transfer_ms += xfer_ms
                self._decisions.append({
                    "path": "two_stage", "prefill": pre_rid,
                    "decode": rid, "pages": moved,
                    "prompt_tokens": len(prompt),
                    "loads": dict(loads),
                    "modeled_flops": dict(mloads)})
            self._finish_two_stage(proxy, rep, req)
            return
        with self._lock:
            self._rejected += 1
        raise last_err if last_err is not None else FleetUnavailable(
            "router: no decode replica accepted the hand-off")

    def _finish_two_stage(self, proxy, rep, req) -> None:
        """Wire the decode replica's live request back onto the proxy:
        attach ids, honour a pre-attach cancel, pump the token stream,
        and mirror the final outcome."""
        proxy.attach(req)
        if proxy.cancelled:
            try:
                rep.cancel(req)
            except Exception:  # noqa: BLE001
                pass
        inner_q = getattr(req, "stream_q", None)
        if proxy.stream_q is not None and inner_q is not None:
            while True:
                try:
                    tok = inner_q.get(timeout=self.handoff_timeout_s)
                except queue_mod.Empty:
                    break  # engine hung: finalize below reports it
                proxy.stream_q.put(tok)
                if tok is None:
                    break
        done = getattr(req, "done", None)
        if done is not None:
            done.wait(timeout=self.handoff_timeout_s)
        proxy.finalize(req)

    def cancel(self, req) -> None:
        if isinstance(req, _RecoverableRequest):
            req.cancelled = True  # stops any further resubmit
            req = req._inner      # fall through: cancel the live inner
        if isinstance(req, _HandoffRequest):
            req.cancelled = True  # pre-attach: the orchestration
            # thread sees it and cancels on arrival
            if req.inner is None:
                return
            req = req.inner
        rep = self._by_id.get(getattr(req, "replica_id", None))
        if rep is None:
            _logger.warning("router.cancel: request %r names no known "
                            "replica", getattr(req, "rid", None))
            return
        rep.cancel(req)

    # -- aggregated observability -----------------------------------------

    def router_stats(self) -> dict:
        # probe-backoff gauge reads replica state OUTSIDE the lock
        # (HTTPReplica accessors are plain attribute reads, but the
        # replica list itself may be mid-scale — snapshot it)
        backoff = 0.0
        for rep in list(self.replicas):
            fn = getattr(rep, "reprobe_backoff_s", None)
            if fn is not None:
                try:
                    backoff = max(backoff, float(fn()))
                except Exception:  # noqa: BLE001 — advisory gauge
                    pass
        with self._lock:
            d = max(self._dispatches, 1)
            out = {
                "router_replicas": len(self.replicas),
                "router_affinity": self.affinity,
                "router_fallback": self.fallback,
                "router_dispatches": self._dispatches,
                "router_affinity_hits": self._affinity_hits,
                "router_affinity_hit_rate": round(
                    self._affinity_hits / d, 4),
                "router_affinity_hit_pages": self._affinity_hit_pages,
                "router_failovers": self._failovers,
                "router_rejected": self._rejected,
                "router_index_entries": len(self._index),
                "router_per_replica_dispatches": dict(self._per_replica),
            }
            if self.disagg:
                # ISSUE 17: gated on disaggregated mode so symmetric
                # fleets keep the byte-compatible legacy /metrics JSON
                out["router_prefill_replicas"] = len(self._prefill_ids)
                out["router_decode_replicas"] = len(self._decode_ids)
                out["serve_prefill_replica"] = self._prefill_dispatches
                out["serve_transfer_pages"] = self._transfer_pages
                out["serve_transfer_ms"] = round(self._transfer_ms, 2)
            if self.ttft_slo_s is not None:
                out["router_slo_rejected"] = self._slo_rejected
            # ISSUE 20: each gated on ITS feature being armed so the
            # unmanaged, non-recovering fleet keeps the legacy schema
            if self.recover_requests:
                out["serve_resubmitted"] = self._resubmitted
            if self._managed:
                out["serve_fleet_replaced"] = self._fleet_replaced
                out["serve_scale_events"] = self._scale_events
            if self._handoff_rejected:
                out["serve_handoff_rejected"] = self._handoff_rejected
            if backoff > 0:
                out["router_reprobe_backoff_s"] = round(backoff, 3)
            return out

    def decision_log(self) -> list:
        """The recent placement decisions (bounded ring): path taken,
        chosen prefill/decode replicas, pages shipped, and the
        loads/modeled-FLOPs snapshot each choice was made from — the
        ISSUE 17 reproducibility trail (the bench re-derives the
        routing from exactly these records)."""
        with self._lock:
            return [dict(dec) for dec in self._decisions]

    def retry_after_s(self) -> float:
        """Honest fleet Retry-After (ISSUE 17 satellite): the SOONEST
        any replica's modeled backlog drains, clamped to [1, 60] s;
        constant 1 s when no replica can model (the legacy header)."""
        vals: List[float] = []
        for rep in self.replicas:
            fn = getattr(rep, "retry_after_s", None)
            if fn is None:
                continue
            try:
                v = fn()
            except Exception:  # noqa: BLE001 — advisory
                v = None
            if v is not None:
                vals.append(float(v))
        if not vals:
            return 1.0
        return float(min(max(min(vals), 1.0), 60.0))

    def counters(self) -> dict:
        """Fleet /metrics: router dispatch stats + additive engine
        counters summed across replicas + per-replica detail under
        "replicas" (keyed by replica id — each row carries its own
        serve_replica_id). Non-additive gauges (percentiles, rates,
        dtypes) stay per-replica only: summing a p95 would fabricate a
        number; the fleet-wide distributions live in the MERGED
        histograms on the Prometheus surface."""
        per = {r.replica_id: r.counters() for r in self.replicas}
        agg: dict = dict(self.router_stats())
        # serve_kv_pool_bytes is PER-CHIP by contract (engine.py
        # ISSUE 14 small fix) — the fleet sum scales each replica by
        # its tp instead (fleet_kv_pool_bytes), under its own key so
        # the two units can never be confused
        agg["serve_kv_pool_bytes_fleet"] = sum(
            r.fleet_kv_pool_bytes() for r in self.replicas)
        additive = (
            "serve_queue_depth",
            "serve_pages_in_use", "serve_pages_free", "serve_admitted",
            "serve_retired", "serve_timed_out", "serve_cancelled",
            "serve_steps", "serve_tok_s", "serve_prefill_tokens",
            "serve_prefix_hit_tokens", "serve_prefix_lookup_tokens",
            "serve_prefix_hits", "serve_prefix_lookups",
            "serve_prefix_cached_pages", "serve_prefix_shared_pages",
            "serve_prefix_cow_copies", "serve_prefix_evicted_pages",
            # device-cost + sentinel aggregates (ISSUE 15): present
            # only on replicas running with the cost registry /
            # sentinel on — the per-request cost records' fleet totals
            "serve_modeled_gflops", "serve_page_rounds",
            "serve_perf_regressions", "serve_perf_bad_rounds",
        )
        for key in additive:
            vals = [c[key] for c in per.values() if key in c]
            if vals:
                agg[key] = round(sum(vals), 2)
        agg["replicas"] = per
        return agg

    def health(self) -> dict:
        """The router's load-balancer probe, same shape the server
        expects from an engine: alive while ANY replica can take
        traffic."""
        per = {r.replica_id: r.health() for r in self.replicas}
        alive = [rid for rid, h in per.items()
                 if h["alive"] and h["broken"] is None]
        return {
            "alive": bool(alive),
            "broken": None if alive else "all replicas down",
            "queue_depth": sum(h["queue_depth"] for h in per.values()),
            "slots_busy": sum(h["slots_busy"] for h in per.values()),
            "replicas": per,
        }

    def histograms(self):
        """Fleet-wide latency histograms: per-name bucket merge across
        replicas (cumulative buckets are additive)."""
        from megatron_llm_tpu.telemetry import Histogram

        by_name: Dict[str, list] = {}
        for rep in self.replicas:
            for h in rep.histograms():
                by_name.setdefault(h.name, []).append(h)
        return [Histogram.merged(hs) for hs in by_name.values()]

    def prometheus_metrics(self) -> str:
        from megatron_llm_tpu.telemetry import render_prometheus

        counters = {k: v for k, v in self.counters().items()
                    if k not in ("replicas",
                                 "router_per_replica_dispatches")}
        return render_prometheus(counters, self.histograms())

    def flight_record(self) -> dict:
        out = {"reason": "on-demand",
               "router": self.router_stats(),
               "replicas": {r.replica_id: r.flight_record()
                            for r in self.replicas}}
        if self.disagg or self.ttft_slo_s is not None:
            # gated like the counters: pre-ISSUE-17 dumps keep their shape
            out["decisions"] = self.decision_log()
        # ISSUE 20: gated on having something to report — a fleet that
        # never lost a replica (and runs unmanaged) keeps legacy shape
        ev = self.evictions()
        if ev:
            out["evictions"] = ev
        if self._controller is not None:
            out["fleet"] = self._controller.flight_events()
        return out

    def request_profile(self, rounds: int,
                        trace_dir: Optional[str] = None,
                        replica: int = 0) -> dict:
        """Arm a profiler capture on ONE replica (jax.profiler is
        process-global — arming N in-process engines at once would
        collide; POST /profile defaults to replica 0)."""
        rep = self._by_id.get(replica)
        if rep is None or not hasattr(rep, "engine"):
            return {"ok": False,
                    "error": f"no in-process replica {replica}"}
        return rep.engine.request_profile(rounds, trace_dir=trace_dir)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        for rep in self.replicas:
            rep.start()
        self._thread = object()  # duck-typed "started" (server.run)

    def drain(self):
        for rep in self.replicas:
            rep.drain()

    def stop(self, drain: bool = True):
        for rep in self.replicas:
            rep.stop(drain=drain)
        self._thread = None
