"""Weighted mix of datasets (ref: megatron/data/blendable_dataset.py:12-60)."""

from __future__ import annotations

import numpy as np

from megatron_llm_tpu.data import helpers


class BlendableDataset:
    def __init__(self, datasets, weights):
        self.datasets = datasets
        assert len(datasets) == len(weights)
        self.size = sum(len(d) for d in datasets)
        weights = np.asarray(weights, np.float64)
        assert np.sum(weights) > 0.0
        weights = weights / np.sum(weights)
        assert len(datasets) < 255
        self.dataset_index, self.dataset_sample_index = helpers.build_blending_indices(
            weights, self.size
        )

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        d = self.dataset_index[idx]
        s = self.dataset_sample_index[idx]
        # modulo guards the 0.5% oversampling headroom (ref behavior relies
        # on each sub-dataset being built slightly larger than needed)
        return self.datasets[d][s % len(self.datasets[d])]
