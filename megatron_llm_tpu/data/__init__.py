from megatron_llm_tpu.data.indexed_dataset import (  # noqa: F401
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    make_dataset,
)
from megatron_llm_tpu.data.gpt_dataset import (  # noqa: F401
    GPTDataset,
    build_train_valid_test_datasets,
)
from megatron_llm_tpu.data.blendable_dataset import BlendableDataset  # noqa: F401
from megatron_llm_tpu.data.bert_dataset import BertDataset  # noqa: F401
from megatron_llm_tpu.data.t5_dataset import T5Dataset  # noqa: F401
from megatron_llm_tpu.data.ict_dataset import ICTDataset  # noqa: F401
from megatron_llm_tpu.data.data_samplers import (  # noqa: F401
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
    build_pretraining_data_loader,
)
from megatron_llm_tpu.data.orqa_wiki_dataset import (  # noqa: F401
    OpenRetrievalEvidenceDataset,
)
from megatron_llm_tpu.data.realm_index import (  # noqa: F401
    MIPSIndex,
    OpenRetrievalDataStore,
)
