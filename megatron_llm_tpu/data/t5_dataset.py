"""T5 span-corruption pretraining dataset.

Parity target: ref megatron/data/t5_dataset.py (`T5Dataset` :28-77,
`build_training_sample` :80-144, `pad_and_convert_to_numpy` :147-216):
geometric-span masking (max_ngrams=10, p=0.2), spans replaced by sentinel
tokens on the encoder side and expanded as sentinel+span on the decoder
side, BOS-shifted decoder input, EOS-terminated target.

The reference emits full 2D/3D attention-mask matrices per sample
(:200-207); here the masks stay 1D keep-vectors — models/t5.py builds the
outer-product + causal forms on device, so the host pipeline ships
seq_len instead of seq_len^2 bytes per sample.
"""

from __future__ import annotations

from typing import List

import numpy as np

from megatron_llm_tpu.data.bert_dataset import get_samples_mapping
from megatron_llm_tpu.data.masked_lm import create_masked_lm_predictions


def pad_and_convert_to_numpy(tokens, masked_positions, masked_labels,
                             pad_id, max_seq_length, max_seq_length_dec,
                             masked_spans, bos_id, eos_id, sentinel_tokens):
    """ref: t5_dataset.py:147-216, with 1D keep-masks instead of dense
    mask matrices (see module docstring)."""
    sentinels = list(sentinel_tokens)
    t5_input: List[int] = []
    t5_decoder_in: List[int] = [bos_id]
    t5_decoder_out: List[int] = []
    start_index = 0
    for span in masked_spans:
        flag = sentinels.pop(0)
        t5_decoder_in.append(flag)
        t5_decoder_in.extend(span.label)
        t5_decoder_out.append(flag)
        t5_decoder_out.extend(span.label)
        t5_input.extend(tokens[start_index:span.index[0]])
        t5_input.append(flag)
        start_index = span.index[-1] + 1
    t5_decoder_out.append(eos_id)
    t5_input.extend(tokens[start_index:])

    num_tokens = len(t5_input)
    padding_length = max_seq_length - num_tokens
    assert padding_length >= 0, (num_tokens, max_seq_length)
    assert len(masked_positions) == len(masked_labels)

    tokens_enc = np.array(t5_input + [pad_id] * padding_length, np.int64)
    num_tokens_dec = len(t5_decoder_in)
    padding_length_dec = max_seq_length_dec - num_tokens_dec
    assert padding_length_dec >= 0, (num_tokens_dec, max_seq_length_dec)
    tokens_dec_in = np.array(t5_decoder_in + [pad_id] * padding_length_dec,
                             np.int64)
    labels = np.array(t5_decoder_out + [-1] * padding_length_dec, np.int64)
    loss_mask = np.array([1] * num_tokens_dec + [0] * padding_length_dec,
                         np.int64)
    enc_mask = np.array([1] * num_tokens + [0] * padding_length, np.int64)
    dec_mask = np.array([1] * num_tokens_dec + [0] * padding_length_dec,
                        np.int64)
    return tokens_enc, tokens_dec_in, labels, enc_mask, dec_mask, loss_mask


def build_training_sample(sample, target_seq_length, max_seq_length,
                          max_seq_length_dec, vocab_id_list,
                          vocab_id_to_token_dict, cls_id, sep_id, mask_id,
                          pad_id, masked_lm_prob, np_rng, bos_id, eos_id,
                          sentinel_tokens) -> dict:
    """ref: t5_dataset.py:80-144."""
    assert target_seq_length <= max_seq_length
    tokens = [t for sentence in sample for t in sentence]
    truncated = len(tokens) > target_seq_length
    tokens = tokens[:target_seq_length]

    max_predictions_per_seq = masked_lm_prob * target_seq_length
    (tokens, masked_positions, masked_labels, _,
     masked_spans) = create_masked_lm_predictions(
        tokens, vocab_id_list, vocab_id_to_token_dict, masked_lm_prob,
        cls_id, sep_id, mask_id, max_predictions_per_seq, np_rng,
        max_ngrams=10, geometric_dist=True, masking_style="t5",
    )
    tokens_enc, tokens_dec_in, labels, enc_mask, dec_mask, loss_mask = \
        pad_and_convert_to_numpy(
            tokens, masked_positions, masked_labels, pad_id, max_seq_length,
            max_seq_length_dec, masked_spans, bos_id, eos_id,
            sentinel_tokens,
        )
    return {
        "text_enc": tokens_enc,
        "text_dec": tokens_dec_in,
        "labels": labels,
        "loss_mask": loss_mask,
        "truncated": int(truncated),
        "enc_mask": enc_mask,
        "dec_mask": dec_mask,
    }


class T5Dataset:
    """ref: T5Dataset t5_dataset.py:28-77."""

    def __init__(self, name, indexed_dataset, data_prefix, num_epochs,
                 max_num_samples, masked_lm_prob, max_seq_length,
                 max_seq_length_dec, short_seq_prob, seed, tokenizer):
        self.name = name
        self.indexed_dataset = indexed_dataset
        self.seed = seed
        self.masked_lm_prob = masked_lm_prob
        self.max_seq_length = max_seq_length
        self.max_seq_length_dec = max_seq_length_dec

        # -2: T5 adds no [CLS]/[SEP] pair but reserves room for sentinel
        # inflation (ref: t5_dataset.py:46 uses max_seq_length - 2)
        self.samples_mapping = get_samples_mapping(
            indexed_dataset, data_prefix, num_epochs, max_num_samples,
            self.max_seq_length - 2, short_seq_prob, seed, name,
            binary_head=False,
        )
        self.vocab_id_list = list(tokenizer.inv_vocab.keys())
        self.vocab_id_to_token_dict = tokenizer.inv_vocab
        self.cls_id = tokenizer.cls
        self.sep_id = tokenizer.sep
        self.mask_id = tokenizer.mask
        self.pad_id = tokenizer.pad
        self.bos_id = tokenizer.bos_token_id
        self.eos_id = tokenizer.eos_token_id
        self.sentinel_tokens = tokenizer.additional_special_tokens_ids
        assert len(self.sentinel_tokens) > 0, \
            "Provide the argument --vocab-extra-ids 100 to the script"

    def __len__(self):
        return self.samples_mapping.shape[0]

    def __getitem__(self, idx):
        start_idx, end_idx, seq_length = self.samples_mapping[idx]
        sample = [np.asarray(self.indexed_dataset[i])
                  for i in range(start_idx, end_idx)]
        np_rng = np.random.RandomState(seed=((self.seed + idx) % 2**32))
        return build_training_sample(
            sample, seq_length, self.max_seq_length, self.max_seq_length_dec,
            self.vocab_id_list, self.vocab_id_to_token_dict, self.cls_id,
            self.sep_id, self.mask_id, self.pad_id, self.masked_lm_prob,
            np_rng, self.bos_id, self.eos_id, self.sentinel_tokens,
        )
