"""Train/valid/test dataset building for BERT and T5 corpora.

Parity target: ref megatron/data/dataset_utils.py
`build_train_valid_test_datasets` / `_build_train_valid_test_datasets`
(:421-594): one sentence-level indexed corpus split by DOCUMENT ranges,
with each split wrapped so its sample maps only cover that range.
"""

from __future__ import annotations

import numpy as np

from megatron_llm_tpu.data.bert_dataset import BertDataset
from megatron_llm_tpu.data.gpt_dataset import get_train_valid_test_split_
from megatron_llm_tpu.data.indexed_dataset import make_dataset
from megatron_llm_tpu.data.t5_dataset import T5Dataset


class DocRangeView:
    """A doc-range window over a sentence-level indexed dataset.

    The reference mutates the dataset with set_doc_idx (dataset_utils.py
    :527-560); a read-only view is safer and equally cheap: doc_idx is
    sliced, sizes/__getitem__ stay absolute (the mapping rows carry
    absolute sentence indices).
    """

    def __init__(self, dataset, start_doc: int, end_doc: int):
        self._ds = dataset
        self.doc_idx = np.asarray(dataset.doc_idx[start_doc:end_doc + 1],
                                  np.int64)

    @property
    def sizes(self):
        return self._ds.sizes

    def __getitem__(self, idx):
        return self._ds[idx]

    def __len__(self):
        return len(self._ds)


def build_train_valid_test_datasets(
    data_prefix,
    splits_string: str,
    train_valid_test_num_samples,
    max_seq_length: int,
    masked_lm_prob: float,
    short_seq_prob: float,
    seed: int,
    tokenizer,
    dataset_type: str = "standard_bert",
    binary_head: bool = True,
    max_seq_length_dec=None,
    data_impl: str = "mmap",
):
    """ref: dataset_utils.py:421-594 (single-corpus path; blending rides
    BlendableDataset exactly like GPT)."""
    if not isinstance(data_prefix, (str,)):
        assert len(data_prefix) == 1, \
            "multi-corpus bert/t5 blending: pass one prefix per call"
        data_prefix = data_prefix[0]

    indexed = make_dataset(data_prefix, data_impl)
    total_docs = len(indexed.doc_idx) - 1
    splits = get_train_valid_test_split_(splits_string, total_docs)

    def build_split(index, name):
        if splits[index + 1] <= splits[index]:
            return None
        # A split whose requested sample budget is zero (e.g. the test split
        # when no test iterations are scheduled) must not be built:
        # get_samples_mapping requires max_num_samples>0 or num_epochs
        # (the reference always passes test_iters*global_batch_size,
        # ref: training.py build_train_valid_test_data_iterators).
        if not train_valid_test_num_samples[index]:
            return None
        view = DocRangeView(indexed, splits[index], splits[index + 1])
        kwargs = dict(
            name=name,
            indexed_dataset=view,
            data_prefix=data_prefix,
            num_epochs=None,
            max_num_samples=train_valid_test_num_samples[index],
            masked_lm_prob=masked_lm_prob,
            max_seq_length=max_seq_length,
            short_seq_prob=short_seq_prob,
            seed=seed,
            tokenizer=tokenizer,
        )
        if dataset_type == "t5":
            return T5Dataset(max_seq_length_dec=max_seq_length_dec, **kwargs)
        return BertDataset(binary_head=binary_head, **kwargs)

    return (build_split(0, "train"), build_split(1, "valid"),
            build_split(2, "test"))
