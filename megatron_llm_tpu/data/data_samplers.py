"""Pretraining samplers + loader.

Parity target: ref megatron/data/data_samplers.py. One structural change:
the reference is SPMD — every GPU process runs a sampler emitting its own
per-rank microbatches (contiguous chunk `dp_rank*mbs` of each global
microbatch, ref :48-118). JAX is single-controller: the host assembles the
GLOBAL microbatch of shape (mbs*dp, seq) in exactly the reference's
concatenated rank order, and the `data`-axis sharding hands rank r the same
contiguous chunk the reference's rank-r sampler would have loaded. Sample
order, and therefore the loss curve, is preserved.

Resume semantics via `consumed_samples` match ref :14-46 and
training.py:861-868.
"""

from __future__ import annotations

import numpy as np


class MegatronPretrainingSampler:
    """Sequential strided sampler (ref: data_samplers.py:48-118)."""

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        micro_batch_size: int,
        data_parallel_size: int,
        drop_last: bool = True,
    ):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )
        self.drop_last = drop_last
        assert self.total_samples > 0
        assert self.consumed_samples < self.total_samples

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.micro_batch_times_data_parallel_size:
                yield batch  # the GLOBAL microbatch, rank chunks contiguous
                batch = []
        if len(batch) > 0 and not self.drop_last:
            yield batch


class MegatronPretrainingRandomSampler:
    """Epoch-seeded shuffling sampler (ref: data_samplers.py:119-186).

    NOTE: the reference shuffles with torch.Generator(seed=epoch); we use
    numpy RandomState(seed=epoch) — same structure (per-epoch reshuffle of
    the unconsumed bucket), different permutation stream.
    """

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        micro_batch_size: int,
        data_parallel_size: int,
    ):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )
        self.last_batch_size = (
            self.total_samples % self.micro_batch_times_data_parallel_size
        )
        assert self.total_samples > 0

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        active_total_samples = self.total_samples - self.last_batch_size
        epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples
        assert current_epoch_samples % self.micro_batch_times_data_parallel_size == 0

        g = np.random.RandomState(seed=epoch)
        idx_range = g.permutation(active_total_samples)[current_epoch_samples:]

        batch = []
        for idx in idx_range:
            batch.append(int(idx))
            if len(batch) == self.micro_batch_times_data_parallel_size:
                self.consumed_samples += len(batch)
                yield batch
                batch = []


class PretrainingDataLoader:
    """Assembles (num_microbatches, mbs*dp, seq+1) int32 'text' arrays.

    The reference leans on torch DataLoader workers (ref:
    data_samplers.py:40-46); here sample fetch is a zero-copy mmap read, so
    a plain loop keeps up with the device step. An iterator interface keeps
    it swappable for a background-thread prefetcher.
    """

    def __init__(self, dataset, sampler, num_microbatches=1, keys=None,
                 row_range=None):
        self.dataset = dataset
        self.sampler = sampler
        # int, or a zero-arg callable consulted each step — that's how the
        # batch-size rampup reaches the loader (ref: the reference re-reads
        # get_num_microbatches() every train_step, training.py:403).
        self.num_microbatches = num_microbatches
        # None -> GPT 'text' arrays; a list of keys -> dict batches with
        # every key stacked to (num_micro, mbs*dp, ...) — how the BERT/T5
        # multi-field samples ride the same loader.
        self.keys = keys
        # multi-host: [lo, hi) slice of each global microbatch this PROCESS
        # loads (parallel/multihost.process_row_range) — the sampler's
        # bookkeeping stays global (consumed_samples counts every row),
        # only the fetch is local, so no host duplicates another's I/O
        # (ref analogue: per-rank strided samplers, data_samplers.py:48-118)
        self.row_range = row_range

    def __iter__(self):
        it = iter(self.sampler)
        while True:
            n = self.num_microbatches() if callable(self.num_microbatches) \
                else self.num_microbatches
            micros = []
            try:
                for _ in range(n):
                    idxs = next(it)
                    if self.row_range is not None:
                        idxs = idxs[self.row_range[0]:self.row_range[1]]
                    if self.keys is None:
                        micros.append(np.stack(
                            [self.dataset[i]["text"] for i in idxs]
                        ).astype(np.int32))
                    else:
                        samples = [self.dataset[i] for i in idxs]
                        micros.append({
                            k: np.stack([s[k] for s in samples]).astype(
                                np.int32
                            )
                            for k in self.keys
                        })
            except StopIteration:
                return
            if self.keys is None:
                yield np.stack(micros)
            else:
                yield {
                    k: np.stack([m[k] for m in micros]) for k in self.keys
                }


def build_pretraining_data_loader(
    dataset,
    consumed_samples: int,
    micro_batch_size: int,
    data_parallel_size: int,
    num_microbatches=1,  # int or zero-arg callable (rampup)
    dataloader_type: str = "single",
    drop_last: bool = True,
    keys=None,
    row_range=None,
):
    """ref: build_pretraining_data_loader (data_samplers.py:14-46).

    `row_range`: multi-host [lo, hi) slice of each global microbatch this
    process loads (see PretrainingDataLoader). Entry points pass
    `multihost.process_row_range(ctx, mbs*dp)` when process_count > 1."""
    if dataset is None:
        return None
    if dataloader_type == "single":
        sampler = MegatronPretrainingSampler(
            total_samples=len(dataset),
            consumed_samples=consumed_samples,
            micro_batch_size=micro_batch_size,
            data_parallel_size=data_parallel_size,
            drop_last=drop_last,
        )
    elif dataloader_type == "cyclic":
        sampler = MegatronPretrainingRandomSampler(
            total_samples=len(dataset),
            consumed_samples=consumed_samples,
            micro_batch_size=micro_batch_size,
            data_parallel_size=data_parallel_size,
        )
    else:
        raise ValueError(f"unknown dataloader type {dataloader_type}")
    return PretrainingDataLoader(dataset, sampler, num_microbatches,
                                 keys=keys, row_range=row_range)
