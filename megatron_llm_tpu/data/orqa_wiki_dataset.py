"""Open-retrieval wiki evidence dataset.

Parity target: ref megatron/data/orqa_wiki_dataset.py —
`OpenRetrievalEvidenceDataset` (:122-178) reading the DPR-format evidence
TSV (`id \t text \t title`) and serving per-row samples for the indexer
job. The reference tokenizes eagerly into fixed-length id/type/pad arrays
for its torch DataLoader; here rows stay text until the embedding batch is
formed (the biencoder's `embed_text` tokenizes host-side, one compiled
shape per batch — tasks/orqa/evaluate.py's convention), so the dataset is
a thin indexable view over the TSV.
"""

from __future__ import annotations

import csv
from typing import List, Tuple


class OpenRetrievalEvidenceDataset:
    """ref: OpenRetrievalEvidenceDataset (orqa_wiki_dataset.py:122-178)."""

    def __init__(self, datapath: str, name: str = "evidence"):
        self.name = name
        self.samples = self.process_samples_from_single_path(datapath)
        print(f" > loaded {len(self.samples)} evidence rows from "
              f"{datapath}", flush=True)

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, idx: int) -> dict:
        row_id, text, title = self.samples[idx]
        return {"row_id": row_id, "text": text, "title": title}

    @staticmethod
    def process_samples_from_single_path(
        filename: str,
    ) -> List[Tuple[int, str, str]]:
        """ref :164-178: skip the header row; the DPR convention keeps
        ids 1-based in-file."""
        rows = []
        with open(filename, encoding="utf-8") as f:
            reader = csv.reader(f, delimiter="\t")
            for i, row in enumerate(reader):
                if i == 0 and row and row[0] in ("id", "﻿id"):
                    continue  # header
                if len(row) < 2:
                    continue
                title = row[2] if len(row) > 2 else ""
                rows.append((int(row[0]), row[1], title))
        return rows
