"""GPT pretraining dataset: document stitching + cached index mappings.

Behavioral parity with ref megatron/data/gpt_dataset.py — identical doc_idx
/ sample_idx / shuffle_idx construction (same RNG consumption order, same
cache filenames) so a run on the same corpus produces the same sample order
as the reference, which is what makes loss-vs-step comparable (SURVEY.md §7
hard part (e)). Multi-process coordination uses jax.process_index() instead
of torch.distributed rank (only process 0 builds, others poll the cache
files).
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import numpy as np

from megatron_llm_tpu.data import helpers
from megatron_llm_tpu.data.blendable_dataset import BlendableDataset
from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDataset, make_dataset


def get_datasets_weights_and_num_samples(data_prefix, train_valid_test_num_samples):
    """ref: dataset_utils.py get_datasets_weights_and_num_samples — parse
    [w1, p1, w2, p2, ...] and scale per-dataset sample counts (with the
    reference's 0.5% oversampling headroom)."""
    assert len(data_prefix) % 2 == 0
    num_datasets = len(data_prefix) // 2
    weights = [float(data_prefix[2 * i]) for i in range(num_datasets)]
    prefixes = [str(data_prefix[2 * i + 1]) for i in range(num_datasets)]
    total = sum(weights)
    weights = [w / total for w in weights]
    datasets_train_valid_test_num_samples = []
    for w in weights:
        datasets_train_valid_test_num_samples.append(
            [int(np.ceil(n * w * 1.005)) for n in train_valid_test_num_samples]
        )
    return prefixes, weights, datasets_train_valid_test_num_samples


class GPTDataset:
    """ref: GPTDataset (gpt_dataset.py:221-269)."""

    def __init__(
        self,
        name: str,
        data_prefix: str,
        documents: np.ndarray,
        indexed_dataset: MMapIndexedDataset,
        num_samples: int,
        seq_length: int,
        seed: int,
        build_cache: bool = True,
    ):
        self.name = name
        self.indexed_dataset = indexed_dataset
        assert np.min(documents) >= 0
        assert np.max(documents) < indexed_dataset.sizes.shape[0]
        self.doc_idx, self.sample_idx, self.shuffle_idx = _build_index_mappings(
            name, data_prefix, documents, indexed_dataset.sizes, num_samples,
            seq_length, seed, build_cache=build_cache,
        )

    def __len__(self):
        # sample i -> [sample_idx[i], sample_idx[i+1]) (ref :238-241)
        return self.sample_idx.shape[0] - 1

    def __getitem__(self, idx):
        # Stitch documents into one seq_length+1 token sample (ref :243-269).
        idx = self.shuffle_idx[idx]
        doc_f, off_f = self.sample_idx[idx]
        doc_l, off_l = self.sample_idx[idx + 1]
        if doc_f == doc_l:
            sample = self.indexed_dataset.get(
                self.doc_idx[doc_f], offset=off_f, length=off_l - off_f + 1
            )
        else:
            parts = [self.indexed_dataset.get(self.doc_idx[doc_f], offset=off_f)]
            for i in range(doc_f + 1, doc_l):
                parts.append(self.indexed_dataset.get(self.doc_idx[i]))
            parts.append(self.indexed_dataset.get(self.doc_idx[doc_l], length=off_l + 1))
            sample = np.concatenate(parts)
        return {"text": np.asarray(sample, np.int64)}


def _num_tokens(documents, sizes) -> int:
    return int(np.sum(sizes[documents]))


def _num_epochs(tokens_per_epoch, seq_length, num_samples) -> int:
    """ref: gpt_dataset.py:414-425 (the -1 is the boundary-token overlap)."""
    num_epochs = 0
    total_tokens = 0
    while True:
        num_epochs += 1
        total_tokens += tokens_per_epoch
        if (total_tokens - 1) // seq_length >= num_samples:
            return num_epochs


def _build_doc_idx(documents, num_epochs, np_rng, separate_last_epoch):
    """ref: gpt_dataset.py:428-442 — same RNG call order."""
    if not separate_last_epoch or num_epochs == 1:
        doc_idx = np.mgrid[0:num_epochs, 0 : len(documents)][1]
        doc_idx[:] = documents
        doc_idx = doc_idx.reshape(-1).astype(np.int32)
        np_rng.shuffle(doc_idx)
        return doc_idx
    doc_idx_first = _build_doc_idx(documents, num_epochs - 1, np_rng, False)
    doc_idx_last = _build_doc_idx(documents, 1, np_rng, False)
    return np.concatenate((doc_idx_first, doc_idx_last))


def _build_shuffle_idx(num_samples, total_size, np_rng):
    """ref: gpt_dataset.py:494-513 — first/last-epoch split shuffle."""
    dtype_ = np.uint32
    if total_size >= (np.iinfo(np.uint32).max - 1):
        dtype_ = np.int64
    shuffle_idx_first = np.arange(0, num_samples, dtype=dtype_)
    np_rng.shuffle(shuffle_idx_first)
    if num_samples == total_size:
        return shuffle_idx_first
    shuffle_idx_last = np.arange(num_samples, total_size, dtype=dtype_)
    np_rng.shuffle(shuffle_idx_last)
    return np.concatenate((shuffle_idx_first, shuffle_idx_last))


def _is_lead_process() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def _build_index_mappings(
    name, data_prefix, documents, sizes, num_samples, seq_length, seed,
    build_cache: bool = True,
):
    """ref: gpt_dataset.py:272-406 — identical cache naming + construction;
    in-memory build when build_cache=False (tests, tiny runs)."""
    tokens_per_epoch = _num_tokens(documents, sizes)
    num_epochs = _num_epochs(tokens_per_epoch, seq_length, num_samples)
    np_rng = np.random.RandomState(seed=seed)

    _filename = data_prefix
    _filename += f"_{name}_indexmap"
    _filename += f"_{num_samples}ns"
    _filename += f"_{seq_length}sl"
    _filename += f"_{seed}s"
    doc_idx_filename = _filename + "_doc_idx.npy"
    sample_idx_filename = _filename + "_sample_idx.npy"
    shuffle_idx_filename = _filename + "_shuffle_idx.npy"

    cached = all(
        os.path.isfile(f)
        for f in (doc_idx_filename, sample_idx_filename, shuffle_idx_filename)
    )

    if not cached:
        # separate-last-epoch decision (ref :305-341)
        if num_epochs == 1:
            separate_last_epoch = False
        else:
            num_samples_from_epochs_minus_one = (
                (num_epochs - 1) * tokens_per_epoch - 1
            ) // seq_length
            last_epoch_num_samples = num_samples - num_samples_from_epochs_minus_one
            assert last_epoch_num_samples >= 0
            num_samples_per_epoch = (tokens_per_epoch - 1) // seq_length
            assert last_epoch_num_samples < num_samples_per_epoch + 1
            separate_last_epoch = last_epoch_num_samples < int(
                0.80 * num_samples_per_epoch
            )

        if _is_lead_process() or not build_cache:
            doc_idx = _build_doc_idx(documents, num_epochs, np_rng, separate_last_epoch)
            sample_idx = helpers.build_sample_idx(
                sizes, doc_idx, seq_length, num_epochs, tokens_per_epoch
            )
            if separate_last_epoch:
                num_samples_ = num_samples_from_epochs_minus_one
            else:
                num_samples_ = sample_idx.shape[0] - 1
            shuffle_idx = _build_shuffle_idx(
                num_samples_, sample_idx.shape[0] - 1, np_rng
            )
            if not build_cache:
                return doc_idx, sample_idx, shuffle_idx
            # write-temp + atomic rename: non-lead processes poll bare
            # os.path.isfile, so a half-written .npy must never be visible
            # (the reference leans on its torch barrier instead,
            # gpt_dataset.py:378-386)
            for fname, arr in (
                (doc_idx_filename, doc_idx),
                (sample_idx_filename, sample_idx),
                (shuffle_idx_filename, shuffle_idx),
            ):
                tmp = f"{fname}.tmp{os.getpid()}.npy"
                with open(tmp, "wb") as f:
                    np.save(f, arr, allow_pickle=True)
                os.replace(tmp, fname)
        else:
            # non-lead processes wait for the cache (ref pseudo-barrier :378-386)
            deadline = time.time() + 600
            while not all(
                os.path.isfile(f)
                for f in (doc_idx_filename, sample_idx_filename, shuffle_idx_filename)
            ):
                if time.time() > deadline:
                    raise TimeoutError("index mapping cache never appeared")
                time.sleep(1)

    doc_idx = np.load(doc_idx_filename, allow_pickle=True, mmap_mode="r")
    sample_idx = np.load(sample_idx_filename, allow_pickle=True, mmap_mode="r")
    shuffle_idx = np.load(shuffle_idx_filename, allow_pickle=True, mmap_mode="r")
    return doc_idx, sample_idx, shuffle_idx


def get_train_valid_test_split_(splits_string, size):
    """ref: dataset_utils.py:get_train_valid_test_split_ — '969,30,1' style."""
    splits = []
    if splits_string.find(",") != -1:
        splits = [float(s) for s in splits_string.split(",")]
    elif splits_string.find("/") != -1:
        splits = [float(s) for s in splits_string.split("/")]
    else:
        splits = [float(splits_string)]
    while len(splits) < 3:
        splits.append(0.0)
    splits = splits[:3]
    splits_sum = sum(splits)
    assert splits_sum > 0.0
    splits = [split / splits_sum for split in splits]
    splits_index = [0]
    for index, split in enumerate(splits):
        splits_index.append(splits_index[index] + int(round(split * float(size))))
    diff = splits_index[-1] - size
    for index in range(1, len(splits_index)):
        splits_index[index] -= diff
    assert len(splits_index) == 4
    assert splits_index[-1] == size
    return splits_index


def _build_single(
    data_prefix, data_impl, splits_string, train_valid_test_num_samples,
    seq_length, seed, build_cache=True,
):
    """ref: _build_train_valid_test_datasets (gpt_dataset.py:131-218)."""
    indexed_dataset = make_dataset(data_prefix, data_impl)
    total_num_docs = indexed_dataset.sizes.shape[0]
    splits = get_train_valid_test_split_(splits_string, total_num_docs)

    def build_dataset(index, name):
        if splits[index + 1] <= splits[index]:
            return None
        documents = np.arange(splits[index], splits[index + 1], dtype=np.int32)
        return GPTDataset(
            name, data_prefix, documents, indexed_dataset,
            train_valid_test_num_samples[index], seq_length, seed,
            build_cache=build_cache,
        )

    return (
        build_dataset(0, "train"),
        build_dataset(1, "valid"),
        build_dataset(2, "test"),
    )


def build_train_valid_test_datasets(
    data_prefix,
    data_impl: str = "mmap",
    splits_string: str = "969,30,1",
    train_valid_test_num_samples: Sequence[int] = (0, 0, 0),
    seq_length: int = 2048,
    seed: int = 1234,
    train_data_prefix=None,
    valid_data_prefix=None,
    test_data_prefix=None,
    build_cache: bool = True,
):
    """ref: build_train_valid_test_datasets (gpt_dataset.py:20-128):
    single corpus, weighted multi-corpus blend, or separate
    train/valid/test prefixes."""
    if data_prefix is not None:
        if isinstance(data_prefix, (str, os.PathLike)):
            return _build_single(
                data_prefix, data_impl, splits_string,
                train_valid_test_num_samples, seq_length, seed, build_cache,
            )
        if len(data_prefix) == 1:
            return _build_single(
                data_prefix[0], data_impl, splits_string,
                train_valid_test_num_samples, seq_length, seed, build_cache,
            )
        # blended multi-corpus (ref :44-76)
        prefixes, weights, per_ds_nums = get_datasets_weights_and_num_samples(
            data_prefix, train_valid_test_num_samples
        )
        train_sets, valid_sets, test_sets = [], [], []
        for prefix, nums in zip(prefixes, per_ds_nums):
            tr, va, te = _build_single(
                prefix, data_impl, splits_string, nums, seq_length, seed,
                build_cache,
            )
            if tr:
                train_sets.append(tr)
            if va:
                valid_sets.append(va)
            if te:
                test_sets.append(te)
        blend = lambda ds: BlendableDataset(ds, weights) if ds else None
        return blend(train_sets), blend(valid_sets), blend(test_sets)

    # separate prefixes per split (ref :78-128); each split may itself be
    # a weighted blend (ref _build_dataset :100-128)
    def single(prefix, name, n):
        if prefix is None:
            return None
        if isinstance(prefix, (list, tuple)):
            if len(prefix) == 1:
                prefix = prefix[0]
            else:
                prefixes, weights, per_ds_n = \
                    get_datasets_weights_and_num_samples(prefix, [n])
                parts = [single(p, name, nn[0])
                         for p, nn in zip(prefixes, per_ds_n)]
                parts = [p for p in parts if p]
                return BlendableDataset(parts, weights) if parts else None
        ds = make_dataset(prefix, data_impl)
        documents = np.arange(ds.sizes.shape[0], dtype=np.int32)
        return GPTDataset(name, prefix, documents, ds, n, seq_length, seed,
                          build_cache=build_cache)

    return (
        single(train_data_prefix, "train", train_valid_test_num_samples[0]),
        single(valid_data_prefix, "valid", train_valid_test_num_samples[1]),
        single(test_data_prefix, "test", train_valid_test_num_samples[2]),
    )
