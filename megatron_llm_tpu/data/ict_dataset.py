"""Inverse Cloze Task (ICT) dataset for biencoder pretraining.

Parity target: ref megatron/data/ict_dataset.py (`ICTDataset` :50-158)
plus the block-sample cache of realm_dataset_utils.get_block_samples_mapping
(:156-201), whose index comes from the native `build_blocks_mapping`
(data/csrc/helpers.cpp). A sample is a (pseudo-query sentence, evidence
block) pair: the query is one random sentence of the block and is removed
from it 1 - query_in_block_prob of the time.
"""

from __future__ import annotations

import itertools
import os
import random
import time

import numpy as np

from megatron_llm_tpu.data import helpers


def get_block_samples_mapping(block_dataset, title_dataset, data_prefix,
                              num_epochs, max_num_samples, max_seq_length,
                              seed, name, use_one_sent_docs=False,
                              build_cache: bool = True) -> np.ndarray:
    """Cached (start_sent, end_sent, doc, block_id) rows
    (ref: realm_dataset_utils.py:156-201)."""
    if not num_epochs:
        if not max_num_samples:
            raise ValueError(
                "Need to specify either max_num_samples or num_epochs"
            )
        num_epochs = np.iinfo(np.int32).max - 1
    if not max_num_samples:
        max_num_samples = np.iinfo(np.int64).max - 1

    fname = data_prefix + f"_{name}_ict_indexmap"
    if num_epochs != (np.iinfo(np.int32).max - 1):
        fname += f"_{num_epochs}ep"
    if max_num_samples != (np.iinfo(np.int64).max - 1):
        fname += f"_{max_num_samples}mns"
    fname += f"_{max_seq_length}msl_{seed}s.npy"

    if not os.path.isfile(fname):
        t0 = time.time()
        titles_sizes = np.asarray(title_dataset.sizes, np.int32)
        mapping = helpers.build_blocks_mapping(
            np.asarray(block_dataset.doc_idx, np.int64),
            np.asarray(block_dataset.sizes, np.int32),
            titles_sizes, num_epochs, max_num_samples,
            # -3 for [CLS] + 2x[SEP] (ref: realm_dataset_utils.py:183)
            max_seq_length - 3, seed, use_one_sent_blocks=use_one_sent_docs,
        )
        if not build_cache:
            return mapping
        tmp = f"{fname}.tmp{os.getpid()}.npy"
        with open(tmp, "wb") as f:
            np.save(f, mapping, allow_pickle=True)
        os.replace(tmp, fname)
        print(f" > built block samples mapping ({len(mapping)} blocks, "
              f"{time.time() - t0:.2f}s)", flush=True)
    return np.load(fname, allow_pickle=True, mmap_mode="r")


class ICTDataset:
    """ref: ICTDataset ict_dataset.py:50-158."""

    def __init__(self, name, block_dataset, title_dataset, data_prefix,
                 num_epochs, max_num_samples, max_seq_length,
                 query_in_block_prob, seed, tokenizer, use_titles=True,
                 use_one_sent_docs=False):
        self.name = name
        self.seed = seed
        self.max_seq_length = max_seq_length
        self.query_in_block_prob = query_in_block_prob
        self.block_dataset = block_dataset
        self.title_dataset = title_dataset
        self.rng = random.Random(seed)
        self.use_titles = use_titles
        self.use_one_sent_docs = use_one_sent_docs

        self.samples_mapping = get_block_samples_mapping(
            block_dataset, title_dataset, data_prefix, num_epochs,
            max_num_samples, max_seq_length, seed, name, use_one_sent_docs,
        )
        self.cls_id = tokenizer.cls
        self.sep_id = tokenizer.sep
        self.pad_id = tokenizer.pad

    def __len__(self):
        return len(self.samples_mapping)

    def __getitem__(self, idx):
        start_idx, end_idx, doc_idx, block_idx = (
            int(x) for x in self.samples_mapping[idx]
        )
        if self.use_titles:
            title = list(np.asarray(self.title_dataset[doc_idx]))
            title_pad_offset = 3 + len(title)
        else:
            title = None
            title_pad_offset = 2
        block = [list(np.asarray(self.block_dataset[i]))
                 for i in range(start_idx, end_idx)]
        assert (len(block) > 1 or self.use_one_sent_docs
                or self.query_in_block_prob == 1)

        rand_sent_idx = self.rng.randint(0, len(block) - 1)
        if self.rng.random() < self.query_in_block_prob:
            query = list(block[rand_sent_idx])
        else:
            query = block.pop(rand_sent_idx)

        query = query[: self.max_seq_length - 2]
        block_flat = list(itertools.chain(*block))[
            : self.max_seq_length - title_pad_offset
        ]

        query_tokens, query_pad_mask = self.concat_and_pad_tokens(query)
        context_tokens, context_pad_mask = self.concat_and_pad_tokens(
            block_flat, title
        )
        return {
            "query_tokens": query_tokens,
            "query_pad_mask": query_pad_mask,
            "context_tokens": context_tokens,
            "context_pad_mask": context_pad_mask,
            "block_data": np.array([start_idx, end_idx, doc_idx, block_idx],
                                   np.int64),
        }

    def get_block(self, start_idx, end_idx, doc_idx):
        """Evidence block + title, for REALM-style indexing
        (ref: ict_dataset.py:127-136)."""
        block = [list(np.asarray(self.block_dataset[i]))
                 for i in range(start_idx, end_idx)]
        title = list(np.asarray(self.title_dataset[int(doc_idx)]))
        block_flat = list(itertools.chain(*block))[
            : self.max_seq_length - (3 + len(title))
        ]
        return self.concat_and_pad_tokens(block_flat, title)

    def get_null_block(self):
        return self.concat_and_pad_tokens([], [])

    def concat_and_pad_tokens(self, tokens, title=None):
        """[CLS] (title [SEP])? tokens [SEP] + pad (ref: :144-158)."""
        tokens = list(tokens)
        if title is None:
            tokens = [self.cls_id] + tokens + [self.sep_id]
        else:
            tokens = ([self.cls_id] + list(title) + [self.sep_id]
                      + tokens + [self.sep_id])
        assert len(tokens) <= self.max_seq_length, len(tokens)
        num_pad = self.max_seq_length - len(tokens)
        pad_mask = np.array([1] * len(tokens) + [0] * num_pad, np.int64)
        tokens = np.array(tokens + [self.pad_id] * num_pad, np.int64)
        return tokens, pad_mask
