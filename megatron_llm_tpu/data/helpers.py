"""Loader for the native dataset index builders.

ref analogue: megatron/data/dataset_utils.py `compile_helper` +
`from megatron.data import helpers`. Here the C++ is compiled once with g++
into `_helpers.so` next to the source and bound via ctypes; a pure-numpy
fallback keeps everything working when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_SO_PATH = os.path.join(_CSRC, "_helpers.so")
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _compile() -> bool:
    src = os.path.join(_CSRC, "helpers.cpp")
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _SO_PATH, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if not os.path.exists(_SO_PATH) or os.path.getmtime(_SO_PATH) < os.path.getmtime(
        os.path.join(_CSRC, "helpers.cpp")
    ):
        if not _compile():
            return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    lib.build_sample_idx.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.build_blending_indices.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int32,
        ctypes.c_int64,
    ]
    lib.build_mapping.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.c_uint64, ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.build_mapping.restype = ctypes.c_int64
    lib.build_blocks_mapping.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.build_blocks_mapping.restype = ctypes.c_int64
    _LIB = lib
    return lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def build_sample_idx(
    sizes: np.ndarray,
    doc_idx: np.ndarray,
    seq_length: int,
    num_epochs: int,
    tokens_per_epoch: int,
) -> np.ndarray:
    """(num_samples+1, 2) int32 array of (doc_idx_index, doc_offset)
    (ref: helpers.cpp:83-175)."""
    sizes = np.ascontiguousarray(sizes, np.int32)
    doc_idx = np.ascontiguousarray(doc_idx, np.int32)
    num_samples = (num_epochs * tokens_per_epoch - 1) // seq_length
    out = np.zeros((num_samples + 1, 2), np.int32)
    lib = _load()
    if lib is not None:
        lib.build_sample_idx(
            _ptr(sizes, ctypes.c_int32),
            _ptr(doc_idx, ctypes.c_int32),
            seq_length,
            num_epochs,
            tokens_per_epoch,
            _ptr(out, ctypes.c_int32),
        )
        return out
    return _build_sample_idx_np(sizes, doc_idx, seq_length, num_samples)


def _build_sample_idx_np(sizes, doc_idx, seq_length, num_samples):
    """Numpy fallback (ref python twin: gpt_dataset.py:449-491)."""
    out = np.zeros((num_samples + 1, 2), np.int32)
    doc_idx_index = 0
    doc_offset = 0
    for s in range(1, num_samples + 1):
        remaining = seq_length + 1
        while remaining != 0:
            doc_length = sizes[doc_idx[doc_idx_index]] - doc_offset
            remaining -= doc_length
            if remaining <= 0:
                doc_offset += remaining + doc_length - 1
                remaining = 0
            else:
                doc_idx_index += 1
                doc_offset = 0
        out[s, 0] = doc_idx_index
        out[s, 1] = doc_offset
    return out


def build_blending_indices(
    weights: np.ndarray, size: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(dataset_index uint8[size], dataset_sample_index int64[size])
    (ref: helpers.cpp:20-81)."""
    weights = np.ascontiguousarray(weights, np.float64)
    dataset_index = np.zeros(size, np.uint8)
    dataset_sample_index = np.zeros(size, np.int64)
    lib = _load()
    if lib is not None:
        lib.build_blending_indices(
            _ptr(dataset_index, ctypes.c_uint8),
            _ptr(dataset_sample_index, ctypes.c_int64),
            _ptr(weights, ctypes.c_double),
            len(weights),
            size,
        )
        return dataset_index, dataset_sample_index
    # numpy fallback
    current = np.zeros(len(weights), np.int64)
    for i in range(size):
        i_d = max(float(i), 1.0)
        err = weights * i_d - current
        best = int(np.argmax(err))
        dataset_index[i] = best
        dataset_sample_index[i] = current[best]
        current[best] += 1
    return dataset_index, dataset_sample_index


def build_mapping(
    docs: np.ndarray,  # (n_docs+1,) int64 sentence-boundary offsets
    sizes: np.ndarray,  # per-sentence token counts, int32
    num_epochs: int,
    max_num_samples: int,
    max_seq_length: int,
    short_seq_prob: float,
    seed: int,
    min_num_sent: int = 2,
) -> np.ndarray:
    """(num_samples, 3) int64 rows of (start_sent, end_sent, target_len)
    for BERT-style pair datasets (ref: helpers.cpp build_mapping
    :187-452). Two C calls: count, then fill+shuffle."""
    docs = np.ascontiguousarray(docs, np.int64)
    sizes = np.ascontiguousarray(sizes, np.int32)
    lib = _load()
    assert lib is not None, (
        "build_mapping requires the native helpers (g++); the reference "
        "has no python twin for its RNG-dependent mapping either"
    )
    n = lib.build_mapping(
        _ptr(docs, ctypes.c_int64), len(docs), _ptr(sizes, ctypes.c_int32),
        num_epochs, max_num_samples, max_seq_length, short_seq_prob, seed,
        min_num_sent, None,
    )
    out = np.zeros((n, 3), np.int64)
    lib.build_mapping(
        _ptr(docs, ctypes.c_int64), len(docs), _ptr(sizes, ctypes.c_int32),
        num_epochs, max_num_samples, max_seq_length, short_seq_prob, seed,
        min_num_sent, _ptr(out, ctypes.c_int64),
    )
    return out


def build_blocks_mapping(
    docs: np.ndarray,
    sizes: np.ndarray,
    titles_sizes: np.ndarray,  # (n_docs,) int32 title token counts
    num_epochs: int,
    max_num_samples: int,
    max_seq_length: int,
    seed: int,
    use_one_sent_blocks: bool = False,
) -> np.ndarray:
    """(num_samples, 4) int64 rows of (start_sent, end_sent, doc, block_id)
    for ICT/REALM block datasets (ref: helpers.cpp build_blocks_mapping
    :453-680)."""
    docs = np.ascontiguousarray(docs, np.int64)
    sizes = np.ascontiguousarray(sizes, np.int32)
    titles_sizes = np.ascontiguousarray(titles_sizes, np.int32)
    lib = _load()
    assert lib is not None, "build_blocks_mapping requires the native helpers"
    args = (
        _ptr(docs, ctypes.c_int64), len(docs), _ptr(sizes, ctypes.c_int32),
        _ptr(titles_sizes, ctypes.c_int32), num_epochs, max_num_samples,
        max_seq_length, seed, int(use_one_sent_blocks),
    )
    n = lib.build_blocks_mapping(*args, None)
    out = np.zeros((n, 4), np.int64)
    lib.build_blocks_mapping(*args, _ptr(out, ctypes.c_int64))
    return out


def helpers_available() -> bool:
    return _load() is not None
