"""BERT pretraining dataset: sentence pairs + masked LM + SOP labels.

Parity target: ref megatron/data/bert_dataset.py (`BertDataset`,
`build_training_sample` :80-182) and the sample-index cache
`get_samples_mapping` (dataset_utils.py:643-741). The sentence-pair map
comes from the native `build_mapping` (data/csrc/helpers.cpp); samples
reproduce the reference draw-for-draw (same per-sample RandomState
seeding, bert_dataset.py:72-75).
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from megatron_llm_tpu.data import helpers
from megatron_llm_tpu.data.masked_lm import (
    create_masked_lm_predictions,
    create_tokens_and_tokentypes,
    get_a_and_b_segments,
    pad_and_convert_to_numpy,
    truncate_segments,
)


def get_samples_mapping(indexed_dataset, data_prefix, num_epochs,
                        max_num_samples, max_seq_length, short_seq_prob,
                        seed, name, binary_head,
                        build_cache: bool = True) -> np.ndarray:
    """Cached (start_sent, end_sent, target_len) sample map
    (ref: dataset_utils.py:643-741). Single-controller: no barrier needed;
    the cache write is temp+atomic-rename like the GPT index caches."""
    if not num_epochs:
        if not max_num_samples:
            raise ValueError(
                "Need to specify either max_num_samples or num_epochs"
            )
        num_epochs = np.iinfo(np.int32).max - 1
    if not max_num_samples:
        max_num_samples = np.iinfo(np.int64).max - 1

    fname = data_prefix + f"_{name}_indexmap"
    if num_epochs != (np.iinfo(np.int32).max - 1):
        fname += f"_{num_epochs}ep"
    if max_num_samples != (np.iinfo(np.int64).max - 1):
        fname += f"_{max_num_samples}mns"
    fname += f"_{max_seq_length}msl_{short_seq_prob:0.2f}ssp_{seed}s"
    # The split is a DOC-RANGE view; a different --split must not reuse a
    # mapping built for another doc range (the reference shares this wart
    # — its filename omits the range too, dataset_utils.py:653-668).
    doc_idx = np.asarray(indexed_dataset.doc_idx, np.int64)
    fname += f"_{int(doc_idx[0])}-{int(doc_idx[-1])}x{len(doc_idx)}docs.npy"

    if not os.path.isfile(fname):
        t0 = time.time()
        mapping = helpers.build_mapping(
            np.asarray(indexed_dataset.doc_idx, np.int64),
            np.asarray(indexed_dataset.sizes, np.int32),
            num_epochs, max_num_samples, max_seq_length, short_seq_prob,
            seed, min_num_sent=2 if binary_head else 1,
        )
        if not build_cache:
            return mapping
        tmp = f"{fname}.tmp{os.getpid()}.npy"
        with open(tmp, "wb") as f:
            np.save(f, mapping, allow_pickle=True)
        os.replace(tmp, fname)
        print(f" > built and saved samples mapping ({len(mapping)} samples,"
              f" {time.time() - t0:.2f}s) to {fname}", flush=True)
    return np.load(fname, allow_pickle=True, mmap_mode="r")


def build_training_sample(sample, target_seq_length, max_seq_length,
                          vocab_id_list, vocab_id_to_token_dict, cls_id,
                          sep_id, mask_id, pad_id, masked_lm_prob, np_rng,
                          binary_head) -> dict:
    """ref: bert_dataset.py:80-162 — returns the reference's exact field
    set (text/types/labels/is_random/loss_mask/padding_mask/truncated)."""
    if binary_head:
        assert len(sample) > 1
    assert target_seq_length <= max_seq_length

    if binary_head:
        tokens_a, tokens_b, is_next_random = get_a_and_b_segments(sample,
                                                                  np_rng)
    else:
        tokens_a = []
        for s in sample:
            tokens_a.extend(s)
        tokens_b, is_next_random = [], False

    truncated = truncate_segments(tokens_a, tokens_b, len(tokens_a),
                                  len(tokens_b), target_seq_length, np_rng)
    tokens, tokentypes = create_tokens_and_tokentypes(tokens_a, tokens_b,
                                                      cls_id, sep_id)
    max_predictions_per_seq = masked_lm_prob * target_seq_length
    tokens, masked_positions, masked_labels, _, _ = \
        create_masked_lm_predictions(
            tokens, vocab_id_list, vocab_id_to_token_dict, masked_lm_prob,
            cls_id, sep_id, mask_id, max_predictions_per_seq, np_rng,
        )
    tokens_np, tokentypes_np, labels_np, padding_mask_np, loss_mask_np = \
        pad_and_convert_to_numpy(tokens, tokentypes, masked_positions,
                                 masked_labels, pad_id, max_seq_length)
    return {
        "text": tokens_np,
        "types": tokentypes_np,
        "labels": labels_np,
        "is_random": int(is_next_random),
        "loss_mask": loss_mask_np,
        "padding_mask": padding_mask_np,
        "truncated": int(truncated),
    }


class BertDataset:
    """ref: BertDataset bert_dataset.py:28-78."""

    def __init__(self, name, indexed_dataset, data_prefix, num_epochs,
                 max_num_samples, masked_lm_prob, max_seq_length,
                 short_seq_prob, seed, tokenizer,
                 binary_head: bool = True):
        self.name = name
        self.indexed_dataset = indexed_dataset
        self.seed = seed
        self.masked_lm_prob = masked_lm_prob
        self.max_seq_length = max_seq_length
        self.binary_head = binary_head

        # -3 accounts for [CLS] + 2x[SEP] (ref: bert_dataset.py:44)
        self.samples_mapping = get_samples_mapping(
            indexed_dataset, data_prefix, num_epochs, max_num_samples,
            self.max_seq_length - 3, short_seq_prob, seed, name,
            binary_head,
        )
        self.vocab_id_list = list(tokenizer.inv_vocab.keys())
        self.vocab_id_to_token_dict = tokenizer.inv_vocab
        self.cls_id = tokenizer.cls
        self.sep_id = tokenizer.sep
        self.mask_id = tokenizer.mask
        self.pad_id = tokenizer.pad

    def __len__(self):
        return self.samples_mapping.shape[0]

    def __getitem__(self, idx):
        start_idx, end_idx, seq_length = self.samples_mapping[idx]
        sample = [np.asarray(self.indexed_dataset[i])
                  for i in range(start_idx, end_idx)]
        np_rng = np.random.RandomState(seed=((self.seed + idx) % 2**32))
        return build_training_sample(
            sample, seq_length, self.max_seq_length, self.vocab_id_list,
            self.vocab_id_to_token_dict, self.cls_id, self.sep_id,
            self.mask_id, self.pad_id, self.masked_lm_prob, np_rng,
            self.binary_head,
        )
