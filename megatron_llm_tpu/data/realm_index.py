"""Persistent retrieval-embedding store + exact MIPS index.

Parity target: ref megatron/data/realm_index.py —
`OpenRetreivalDataStore` (:17-116; rank-sharded pickle shards, merge) and
`FaissMIPSIndex` (:118-216; faiss flat inner-product search). TPU-first
departures:

- shards are .npz (ids + embeddings matrices), merged by concatenation —
  no pickle, no faiss dependency;
- search is EXACT chunked MIPS on the accelerator: (Q, d) @ (d, chunk)
  with a running `lax.top_k` merge, so the (Q, N) score matrix never
  materializes and evidence streams through the device one chunk at a
  time — the same design the ORQA evaluator proved out
  (tasks/orqa/evaluate.py), factored here so prebuilt indexes and
  on-the-fly evaluation share one implementation.
"""

from __future__ import annotations

import functools
import glob
import os
from typing import Dict, Optional

import numpy as np

from megatron_llm_tpu.analysis.contracts import (
    CompileContract,
    register_contract,
)

register_contract(CompileContract(
    name="realm.chunk_topk",
    max_variants=4,  # one per distinct ((Q, d), (chunk, d), k) a
    # process searches with; the single-executable test guard reads the
    # jit cache through contracts.jit_cache_size
    collectives={"single": frozenset()},
    tmp_bytes_budget=1 << 20,
    notes="module-scope chunk scorer; the padded tail keeps partial "
          "chunks on the same executable (test_msdp_orqa)"))


@functools.lru_cache(maxsize=1)
def _chunk_topk():
    """Module-scope jitted chunk scorer (ADVICE r5: defining+jitting it
    inside search_mips_index re-traced on every call). jit's own cache
    keys on the (Q, d) x (chunk, d) shapes and static k, and the final
    partial chunk is PADDED to chunk_rows by the caller, so one
    executable serves every chunk of every same-shaped search. Pad rows
    are masked to -inf BEFORE top_k (`n_valid` is traced, so it doesn't
    split the executable): a pad row's raw score of 0.0 would otherwise
    displace real negative-score rows inside the chunk's top-k. Lazy via
    lru_cache so importing the data package doesn't pull in jax."""
    import jax
    import jax.numpy as jnp

    # graft-contract: realm.chunk_topk
    @functools.partial(jax.jit, static_argnames=("k",))
    def chunk_topk(q, ev, n_valid, k):
        s = q @ ev.T
        s = jnp.where(jnp.arange(s.shape[-1])[None, :] < n_valid,
                      s, -jnp.inf)
        return jax.lax.top_k(s, k)

    return chunk_topk


class OpenRetrievalDataStore:
    """row_id -> embedding store with rank-sharded writes
    (ref: OpenRetreivalDataStore realm_index.py:17-116)."""

    def __init__(self, embedding_path: str, load_from_path: bool = True,
                 rank: Optional[int] = None):
        # np.savez appends ".npz" to extension-less paths; normalize here
        # so save and load always agree on one file name
        if not embedding_path.endswith(".npz"):
            embedding_path += ".npz"
        self.embedding_path = os.path.abspath(embedding_path)
        if rank is None:
            try:
                import jax

                rank = jax.process_index()
            except Exception:
                rank = 0
        self.rank = rank
        self.embed_data: Dict[int, np.ndarray] = {}
        if load_from_path and os.path.exists(self.embedding_path):
            self.load_from_file()

    # -- ref :37-48 -------------------------------------------------------
    def state(self) -> dict:
        return {"embed_data": self.embed_data}

    def clear(self):
        """Free the embedding data (ref :42-48)."""
        self.embed_data = {}

    # -- ref :50-72 -------------------------------------------------------
    def load_from_file(self):
        with np.load(self.embedding_path) as z:
            ids, embeds = z["ids"], z["embeds"]
        self.embed_data = {int(i): e for i, e in zip(ids, embeds)}
        print(f"> loaded {len(self.embed_data)} embeddings from "
              f"{self.embedding_path}", flush=True)

    def add_block_data(self, row_ids, block_embeds,
                       allow_overwrite: bool = False):
        """Bulk-add (n,) ids + (n, d) embeddings (ref :61-72 adds one at a
        time; vectorized here)."""
        row_ids = np.atleast_1d(np.asarray(row_ids))
        block_embeds = np.atleast_2d(np.asarray(block_embeds, np.float32))
        for rid, emb in zip(row_ids, block_embeds):
            rid = int(rid)
            if not allow_overwrite and rid in self.embed_data:
                raise ValueError(f"duplicate row id {rid}")
            self.embed_data[rid] = emb

    # -- ref :74-116 ------------------------------------------------------
    def _shard_path(self, rank: int) -> str:
        return f"{self.embedding_path}.shard{rank}.npz"

    def save_shard(self):
        """Write this process's shard (ref :74-84)."""
        os.makedirs(os.path.dirname(self.embedding_path) or ".",
                    exist_ok=True)
        ids = np.asarray(sorted(self.embed_data), np.int64)
        embeds = np.stack([self.embed_data[int(i)] for i in ids]) \
            if len(ids) else np.zeros((0, 0), np.float32)
        np.savez(self._shard_path(self.rank), ids=ids, embeds=embeds)

    def merge_shards_and_save(self):
        """Concatenate every shard into the final store and remove the
        shards (ref :86-116). Call from one process after a barrier."""
        ids_all, emb_all = [], []
        shards = sorted(glob.glob(f"{self.embedding_path}.shard*.npz"))
        for path in shards:
            with np.load(path) as z:
                if z["ids"].size:
                    ids_all.append(z["ids"])
                    emb_all.append(z["embeds"])
        ids = np.concatenate(ids_all) if ids_all else np.zeros(0, np.int64)
        if len(set(ids.tolist())) != len(ids):
            raise ValueError("duplicate row ids across shards")
        embeds = np.concatenate(emb_all) if emb_all else \
            np.zeros((0, 0), np.float32)
        np.savez(self.embedding_path, ids=ids, embeds=embeds)
        for path in shards:
            os.remove(path)
        print(f"> merged {len(shards)} shards -> {len(ids)} embeddings at "
              f"{self.embedding_path}", flush=True)


class MIPSIndex:
    """Exact maximum-inner-product search on the accelerator
    (ref: FaissMIPSIndex realm_index.py:118-216 — flat IP index; here the
    'index' is just the (N, d) matrix and search is chunked matmul+top_k,
    exact by construction where faiss-flat is exact by configuration)."""

    def __init__(self, embed_size: int, embed_data=None,
                 chunk_rows: int = 1 << 20):
        self.embed_size = embed_size
        self.chunk_rows = chunk_rows
        self.ids = np.zeros(0, np.int64)
        self.embeds = np.zeros((0, embed_size), np.float32)
        if embed_data is not None:
            self.add_embed_data(embed_data)

    def reset_index(self):
        """ref :165-175."""
        self.ids = np.zeros(0, np.int64)
        self.embeds = np.zeros((0, self.embed_size), np.float32)

    def add_embed_data(self, all_embed_data):
        """Accepts an OpenRetrievalDataStore, its state() dict, or a
        row_id -> embedding dict (ref :186-203)."""
        if isinstance(all_embed_data, OpenRetrievalDataStore):
            data = all_embed_data.embed_data
        elif isinstance(all_embed_data, dict) and "embed_data" in all_embed_data:
            data = all_embed_data["embed_data"]
        else:
            data = all_embed_data
        if not data:
            return
        ids = np.asarray(sorted(data), np.int64)
        embeds = np.stack([np.asarray(data[int(i)], np.float32)
                           for i in ids])
        assert embeds.shape[1] == self.embed_size, embeds.shape
        self.ids = np.concatenate([self.ids, ids])
        self.embeds = np.concatenate([self.embeds, embeds])

    def __len__(self):
        return len(self.ids)

    def search_mips_index(self, query_embeds, top_k: int,
                          reconstruct: bool = False):
        """(Q, d) queries -> (scores (Q, k), ids (Q, k)) — or (scores,
        embeddings (Q, k, d)) when reconstruct (ref :205-216). Chunked
        over the evidence axis with a running top-k merge; every chunk
        (including the final partial one, zero-padded to chunk_rows) hits
        the ONE module-scope jitted executable — no per-call re-tracing
        and no second partial-chunk executable (ADVICE r5)."""
        import jax.numpy as jnp

        q = jnp.asarray(np.asarray(query_embeds, np.float32))
        n = self.embeds.shape[0]
        k = min(top_k, n)
        chunk_topk = _chunk_topk()
        kk = min(k, self.chunk_rows)

        best_s = np.full((q.shape[0], 0), -np.inf, np.float32)
        best_i = np.zeros((q.shape[0], 0), np.int64)
        for lo in range(0, n, self.chunk_rows):
            ev = self.embeds[lo:lo + self.chunk_rows]
            n_valid = ev.shape[0]
            if n_valid < self.chunk_rows:  # pad the final partial chunk
                ev = np.concatenate([
                    ev,
                    np.zeros((self.chunk_rows - n_valid, ev.shape[1]),
                             np.float32),
                ])
            s, i = chunk_topk(q, jnp.asarray(ev), n_valid, kk)
            s = np.asarray(s)
            i = np.asarray(i, np.int64) + lo
            # pad rows arrive already -inf-masked; clamp their ids so the
            # final take stays in range even if one survives the merge
            best_s = np.concatenate([best_s, s], axis=1)
            best_i = np.concatenate([best_i, np.minimum(i, n - 1)], axis=1)
            order = np.argsort(-best_s, axis=1)[:, :k]
            best_s = np.take_along_axis(best_s, order, axis=1)
            best_i = np.take_along_axis(best_i, order, axis=1)
        if reconstruct:
            return best_s, self.embeds[best_i]
        return best_s, self.ids[best_i]
