"""Masked-LM sample construction shared by the BERT and T5 datasets.

Parity target: ref megatron/data/dataset_utils.py — segment pairing
(:95-125), pair truncation (:127-145), [CLS]/[SEP]/tokentype assembly
(:147-176), and `create_masked_lm_predictions` (:187-388): n-gram
whole-word masking with the 80/10/10 BERT corruption or T5's
geometric-span sentinel masking. Same numpy-RandomState call sequence so
samples reproduce the reference's masking decisions draw-for-draw.
"""

from __future__ import annotations

import collections
from typing import List, Sequence, Tuple

import numpy as np

MaskedLmInstance = collections.namedtuple("MaskedLmInstance",
                                          ["index", "label"])


def is_start_piece(piece: str) -> bool:
    """BERT wordpiece convention: continuation pieces start with '##'
    (ref: dataset_utils.py:178-185)."""
    return not piece.startswith("##")


def get_a_and_b_segments(sample: Sequence[List[int]], np_rng):
    """Split a multi-sentence sample into (A, B, is_next_random)
    (ref: :95-125). 50% of the time the segments are swapped — that is the
    sentence-order-prediction negative."""
    n_sentences = len(sample)
    assert n_sentences > 1, "make sure each sample has at least two sentences."
    a_end = 1
    if n_sentences >= 3:
        a_end = np_rng.randint(1, n_sentences)
    tokens_a: List[int] = []
    for j in range(a_end):
        tokens_a.extend(sample[j])
    tokens_b: List[int] = []
    for j in range(a_end, n_sentences):
        tokens_b.extend(sample[j])
    is_next_random = False
    if np_rng.random() < 0.5:
        is_next_random = True
        tokens_a, tokens_b = tokens_b, tokens_a
    return tokens_a, tokens_b, is_next_random


def truncate_segments(tokens_a, tokens_b, len_a, len_b, max_num_tokens,
                      np_rng) -> bool:
    """Trim the pair to max_num_tokens, popping randomly from either end
    of the longer segment (ref: :127-145). Mutates the lists."""
    assert len_a > 0
    if len_a + len_b <= max_num_tokens:
        return False
    while len_a + len_b > max_num_tokens:
        if len_a > len_b:
            len_a -= 1
            tokens = tokens_a
        else:
            len_b -= 1
            tokens = tokens_b
        if np_rng.random() < 0.5:
            del tokens[0]
        else:
            tokens.pop()
    return True


def create_tokens_and_tokentypes(tokens_a, tokens_b, cls_id, sep_id):
    """[CLS] A [SEP] B [SEP] with 0/1 tokentypes (ref: :147-176)."""
    tokens = [cls_id] + list(tokens_a) + [sep_id]
    tokentypes = [0] * (len(tokens_a) + 2)
    if tokens_b:
        tokens += list(tokens_b) + [sep_id]
        tokentypes += [1] * (len(tokens_b) + 1)
    return tokens, tokentypes


def create_masked_lm_predictions(
    tokens: List[int],
    vocab_id_list,
    vocab_id_to_token_dict,
    masked_lm_prob: float,
    cls_id: int,
    sep_id: int,
    mask_id: int,
    max_predictions_per_seq,
    np_rng,
    max_ngrams: int = 3,
    do_whole_word_mask: bool = True,
    favor_longer_ngram: bool = False,
    geometric_dist: bool = False,
    masking_style: str = "bert",
) -> Tuple[List[int], List[int], List[int], List[int], list]:
    """-> (output_tokens, masked_positions, masked_labels, token_boundary,
    masked_spans)  (ref: :187-388, minus the never-used do_permutation arm).

    bert style: 80% [MASK] / 10% keep / 10% random-vocab per position.
    t5 style: every selected position becomes mask_id; the returned
    masked_spans drive the sentinel construction in t5_dataset.
    """
    # group wordpieces into whole-word candidates
    cand_indexes: List[List[int]] = []
    token_boundary = [0] * len(tokens)
    for i, token in enumerate(tokens):
        if token == cls_id or token == sep_id:
            token_boundary[i] = 1
            continue
        if (do_whole_word_mask and cand_indexes
                and not is_start_piece(vocab_id_to_token_dict[token])):
            cand_indexes[-1].append(i)
        else:
            cand_indexes.append([i])
            if is_start_piece(vocab_id_to_token_dict[token]):
                token_boundary[i] = 1

    output_tokens = list(tokens)
    if masked_lm_prob == 0:
        return output_tokens, [], [], token_boundary, []

    num_to_predict = min(max_predictions_per_seq,
                         max(1, int(round(len(tokens) * masked_lm_prob))))

    ngrams = np.arange(1, max_ngrams + 1, dtype=np.int64)
    if not geometric_dist:
        pvals = 1.0 / np.arange(1, max_ngrams + 1)
        pvals /= pvals.sum(keepdims=True)
        if favor_longer_ngram:
            pvals = pvals[::-1]

    # per starting candidate, the list of 1..max_ngrams n-gram windows
    ngram_indexes = []
    for idx in range(len(cand_indexes)):
        ngram_index = [cand_indexes[idx:idx + n] for n in ngrams]
        ngram_indexes.append(ngram_index)
    np_rng.shuffle(ngram_indexes)

    masked_lms: List[MaskedLmInstance] = []
    masked_spans: List[MaskedLmInstance] = []
    covered = set()
    for cand_index_set in ngram_indexes:
        if len(masked_lms) >= num_to_predict:
            break
        if not cand_index_set:
            continue
        if not geometric_dist:
            n = np_rng.choice(
                ngrams[: len(cand_index_set)],
                p=pvals[: len(cand_index_set)]
                / pvals[: len(cand_index_set)].sum(keepdims=True),
            )
        else:
            # SpanBERT p=0.2 geometric, clipped (ref: :276-280)
            n = min(np_rng.geometric(0.2), max_ngrams)

        index_set = sum(cand_index_set[n - 1], [])
        n -= 1
        # back off to shorter n-grams rather than exceed the budget
        while len(masked_lms) + len(index_set) > num_to_predict:
            if n == 0:
                break
            index_set = sum(cand_index_set[n - 1], [])
            n -= 1
        if len(masked_lms) + len(index_set) > num_to_predict:
            continue
        if any(index in covered for index in index_set):
            continue
        for index in index_set:
            covered.add(index)
            if masking_style == "bert":
                if np_rng.random() < 0.8:
                    masked_token = mask_id
                elif np_rng.random() < 0.5:
                    masked_token = tokens[index]
                else:
                    masked_token = vocab_id_list[
                        np_rng.randint(0, len(vocab_id_list))
                    ]
            elif masking_style == "t5":
                masked_token = mask_id
            else:
                raise ValueError(f"invalid masking style {masking_style}")
            output_tokens[index] = masked_token
            masked_lms.append(MaskedLmInstance(index=index,
                                               label=tokens[index]))
        masked_spans.append(MaskedLmInstance(
            index=index_set, label=[tokens[i] for i in index_set]
        ))

    assert len(masked_lms) <= num_to_predict
    # the reference shuffles again here for its (unused) permutation arm
    # (:328); keep the call so the RandomState stream stays draw-for-draw
    # compatible with reference-built samples
    np_rng.shuffle(ngram_indexes)
    masked_lms.sort(key=lambda x: x.index)
    # spans sorted by first position so sentinel order matches text order
    masked_spans.sort(key=lambda x: x.index[0])
    masked_positions = [m.index for m in masked_lms]
    masked_labels = [m.label for m in masked_lms]
    return (output_tokens, masked_positions, masked_labels, token_boundary,
            masked_spans)


def pad_and_convert_to_numpy(tokens, tokentypes, masked_positions,
                             masked_labels, pad_id, max_seq_length):
    """BERT-side padding (ref: :389-419). Labels use -1 filler; callers
    clamp before CE and rely on loss_mask (the reference does the same)."""
    num_tokens = len(tokens)
    padding_length = max_seq_length - num_tokens
    assert padding_length >= 0
    assert len(tokentypes) == num_tokens
    assert len(masked_positions) == len(masked_labels)

    filler = [pad_id] * padding_length
    tokens_np = np.array(tokens + filler, dtype=np.int64)
    tokentypes_np = np.array(tokentypes + filler, dtype=np.int64)
    padding_mask_np = np.array([1] * num_tokens + [0] * padding_length,
                               dtype=np.int64)
    labels = [-1] * max_seq_length
    loss_mask = [0] * max_seq_length
    for pos, lab in zip(masked_positions, masked_labels):
        assert pos < num_tokens
        labels[pos] = lab
        loss_mask[pos] = 1
    return (tokens_np, tokentypes_np, np.array(labels, np.int64),
            padding_mask_np, np.array(loss_mask, np.int64))
