"""Memory-mapped token datasets, byte-compatible with the reference format.

File format (parity with ref megatron/data/indexed_dataset.py:341-448
`MMapIndexedDataset.Index`):

.idx:  b"MMIDIDX\\x00\\x00" | <Q version=1 | <B dtype_code |
       <Q num_sequences | <Q num_docs |
       int32[num_sequences] sizes | int64[num_sequences] byte pointers |
       int64[num_docs] doc_idx (sequence index of each document start)
.bin:  raw token array, C-order, dtype per the code table.

The dtype code table matches ref indexed_dataset.py:95-103 so .bin/.idx
pairs produced by the reference's preprocess_data.py load here unchanged
(and vice versa).
"""

from __future__ import annotations

import os
import shutil
import struct
from typing import Optional

import numpy as np

_HDR_MAGIC = b"MMIDIDX\x00\x00"

# code -> dtype (ref: indexed_dataset.py:95-103; code 6 is python float/f64
# in the reference's table but written as np.float32 by preprocess — we map
# 6 to float32 and 7 to float64 which matches actual reference usage)
DTYPES = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.float32,
    7: np.float64,
    8: np.uint16,
}


def dtype_code(dtype) -> int:
    for k, v in DTYPES.items():
        if v == np.dtype(dtype).type or np.dtype(v) == np.dtype(dtype):
            return k
    raise ValueError(dtype)


def best_fitting_dtype(vocab_size: Optional[int] = None):
    """ref: indexed_dataset.py:31-36."""
    if vocab_size is not None and vocab_size < 65500:
        return np.uint16
    return np.int32


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


class _Index:
    """Reader for the .idx file (mmap-backed)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            magic = f.read(9)
            if magic != _HDR_MAGIC:
                raise ValueError(
                    f"{path}: bad magic {magic!r}; not an MMapIndexedDataset index"
                )
            (version,) = struct.unpack("<Q", f.read(8))
            assert version == 1, version
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(DTYPES[code])
            (self._len,) = struct.unpack("<Q", f.read(8))
            (self._doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()

        self._buffer_mmap = np.memmap(path, mode="r", order="C")
        buf = memoryview(self._buffer_mmap)
        self.sizes = np.frombuffer(buf, np.int32, count=self._len, offset=offset)
        self.pointers = np.frombuffer(
            buf, np.int64, count=self._len, offset=offset + self.sizes.nbytes
        )
        self.doc_idx = np.frombuffer(
            buf,
            np.int64,
            count=self._doc_count,
            offset=offset + self.sizes.nbytes + self.pointers.nbytes,
        )

    def __len__(self):
        return self._len

    def close(self):
        if self._buffer_mmap is not None:
            self._buffer_mmap._mmap.close()
            self._buffer_mmap = None


def write_index(path: str, sizes, doc_idx, dtype) -> None:
    """Write a .idx (parity: Index.writer, ref indexed_dataset.py:346-390)."""
    itemsize = np.dtype(dtype).itemsize
    pointers = np.zeros(len(sizes), np.int64)
    np.cumsum(np.asarray(sizes[:-1], np.int64) * itemsize, out=pointers[1:])
    with open(path, "wb") as f:
        f.write(_HDR_MAGIC)
        f.write(struct.pack("<Q", 1))
        f.write(struct.pack("<B", dtype_code(dtype)))
        f.write(struct.pack("<Q", len(sizes)))
        f.write(struct.pack("<Q", len(doc_idx)))
        f.write(np.asarray(sizes, np.int32).tobytes(order="C"))
        f.write(pointers.tobytes(order="C"))
        f.write(np.asarray(doc_idx, np.int64).tobytes(order="C"))


class MMapIndexedDataset:
    """Reader (parity: ref indexed_dataset.py:341-538)."""

    def __init__(self, path_prefix: str):
        self._path = path_prefix
        self._index = _Index(index_file_path(path_prefix))
        self._bin_mmap = np.memmap(data_file_path(path_prefix), mode="r", order="C")
        self._bin_buffer = memoryview(self._bin_mmap)

    def __len__(self):
        return len(self._index)

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            ptr = self._index.pointers[idx]
            size = self._index.sizes[idx]
            return np.frombuffer(
                self._bin_buffer, self._index.dtype, count=size, offset=ptr
            )
        raise TypeError(idx)

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None):
        """Read a slice of sequence `idx` without loading the rest
        (ref: indexed_dataset.py:521-530)."""
        ptr = self._index.pointers[idx]
        size = self._index.sizes[idx]
        if length is None:
            length = size - offset
        ptr += offset * self._index.dtype.itemsize
        return np.frombuffer(self._bin_buffer, self._index.dtype, count=length, offset=ptr)

    @property
    def sizes(self):
        return self._index.sizes

    @property
    def doc_idx(self):
        return self._index.doc_idx

    @property
    def dtype(self):
        return self._index.dtype

    def close(self):
        self._bin_mmap._mmap.close()
        self._index.close()

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return os.path.exists(index_file_path(path_prefix)) and os.path.exists(
            data_file_path(path_prefix)
        )


class MMapIndexedDatasetBuilder:
    """Writer used by preprocess/merge (ref: indexed_dataset.py:545-585)."""

    def __init__(self, out_file: str, dtype=np.int32):
        self._data_file = open(out_file, "wb")
        self._dtype = np.dtype(dtype)
        self._sizes: list = []
        self._doc_idx = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, another_prefix: str) -> None:
        """Append another dataset (ref: indexed_dataset.py:564-576)."""
        index = _Index(index_file_path(another_prefix))
        assert index.dtype == self._dtype
        offset = len(self._sizes)
        self._sizes.extend(index.sizes.tolist())
        self._doc_idx.extend((index.doc_idx[1:] + offset).tolist())
        index.close()
        with open(data_file_path(another_prefix), "rb") as f:
            shutil.copyfileobj(f, self._data_file)

    def finalize(self, index_file: str) -> None:
        self._data_file.close()
        write_index(index_file, self._sizes, self._doc_idx, self._dtype)


def make_dataset(path_prefix: str, impl: str = "mmap"):
    """ref: make_dataset (indexed_dataset.py:58-78). Only the mmap impl is
    supported (lazy/cached are legacy TNTIDX formats the reference itself
    defaults away from)."""
    if impl in ("mmap", "infer"):
        return MMapIndexedDataset(path_prefix)
    raise ValueError(f"dataset impl {impl!r} not supported (use mmap)")
