// Native dataset index builders for megatron_llm_tpu.
//
// Behavioral parity with the reference's pybind11 extension
// (ref: megatron/data/helpers.cpp:696-701 entry points), re-implemented as
// a plain C ABI consumed through ctypes (no pybind11 in this image).
// The Python wrappers in megatron_llm_tpu/data/helpers.py allocate the
// numpy output buffers and pass raw pointers.
//
// Build: g++ -O3 -shared -fPIC -o _helpers.so helpers.cpp
// (done automatically on first import; see helpers.py)

#include <algorithm>
#include <cstdint>
#include <cstring>

extern "C" {

// Number of (seq_length+1)-token training samples obtainable from
// num_epochs passes over tokens_per_epoch tokens. The -1 mirrors the
// reference's overlap accounting (ref: helpers.cpp:103,
// gpt_dataset.py:414-425): consecutive samples share one boundary token.
int64_t num_samples_from_epochs(int64_t num_epochs, int64_t tokens_per_epoch,
                                int32_t seq_length) {
  return (num_epochs * tokens_per_epoch - 1) / seq_length;
}

// Fill sample_idx[(num_samples+1) x 2] with (doc_idx_index, doc_offset)
// pairs: sample i spans tokens from pair i to pair i+1 inclusive.
// Parity: ref helpers.cpp build_sample_idx (:83-175) / the Python
// equivalent gpt_dataset.py:449-491.
void build_sample_idx(const int32_t* sizes, const int32_t* doc_idx,
                      int32_t seq_length, int64_t num_epochs,
                      int64_t tokens_per_epoch, int32_t* sample_idx) {
  const int64_t num_samples =
      num_samples_from_epochs(num_epochs, tokens_per_epoch, seq_length);

  int64_t doc_idx_index = 0;
  int32_t doc_offset = 0;
  sample_idx[0] = 0;
  sample_idx[1] = 0;

  for (int64_t s = 1; s <= num_samples; ++s) {
    int32_t remaining = seq_length + 1;
    while (remaining != 0) {
      const int32_t doc_length = sizes[doc_idx[doc_idx_index]] - doc_offset;
      remaining -= doc_length;
      if (remaining <= 0) {
        // sample ends inside this document; next sample re-reads the
        // boundary token (the -1)
        doc_offset += remaining + doc_length - 1;
        remaining = 0;
      } else {
        ++doc_idx_index;
        doc_offset = 0;
      }
    }
    sample_idx[2 * s] = static_cast<int32_t>(doc_idx_index);
    sample_idx[2 * s + 1] = doc_offset;
  }
}

// Greedy error-minimising interleave of weighted datasets.
// Parity: ref helpers.cpp build_blending_indices (:20-81) including the
// max(sample_idx, 1.0) detail so sample 0 matches.
void build_blending_indices(uint8_t* dataset_index,
                            int64_t* dataset_sample_index,
                            const double* weights, int32_t num_datasets,
                            int64_t size) {
  int64_t* current = new int64_t[num_datasets]();
  for (int64_t i = 0; i < size; ++i) {
    const double i_d = std::max(static_cast<double>(i), 1.0);
    int64_t best = 0;
    double best_err = weights[0] * i_d - static_cast<double>(current[0]);
    for (int32_t d = 1; d < num_datasets; ++d) {
      const double err = weights[d] * i_d - static_cast<double>(current[d]);
      if (err > best_err) {
        best_err = err;
        best = d;
      }
    }
    dataset_index[i] = static_cast<uint8_t>(best);
    dataset_sample_index[i] = current[best];
    ++current[best];
  }
  delete[] current;
}

}  // extern "C"
