// Native dataset index builders for megatron_llm_tpu.
//
// Behavioral parity with the reference's pybind11 extension
// (ref: megatron/data/helpers.cpp:696-701 entry points), re-implemented as
// a plain C ABI consumed through ctypes (no pybind11 in this image).
// The Python wrappers in megatron_llm_tpu/data/helpers.py allocate the
// numpy output buffers and pass raw pointers.
//
// Build: g++ -O3 -shared -fPIC -o _helpers.so helpers.cpp
// (done automatically on first import; see helpers.py)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <random>

namespace {

constexpr int32_t kLongSentenceLen = 512;  // ref helpers.cpp LONG_SENTENCE_LEN

// ref helpers.cpp:171-185 get_target_sample_len
int32_t target_sample_len(int32_t short_seq_ratio, int32_t max_length,
                          std::mt19937& gen) {
  if (short_seq_ratio == 0) return max_length;
  const uint32_t random_number = gen();
  if ((random_number % short_seq_ratio) == 0) {
    return 2 + random_number % (max_length - 1);
  }
  return max_length;
}

void shuffle_rows(int64_t* maps, int64_t num_samples, int32_t row,
                  int32_t seed) {
  // ref helpers.cpp:393-404 — 64-bit Fisher-Yates with seed+1
  std::mt19937_64 gen(seed + 1);
  for (int64_t i = num_samples - 1; i > 0; --i) {
    const int64_t j = static_cast<int64_t>(gen() % (i + 1));
    for (int32_t c = 0; c < row; ++c) {
      std::swap(maps[row * i + c], maps[row * j + c]);
    }
  }
}

}  // namespace

extern "C" {

// Number of (seq_length+1)-token training samples obtainable from
// num_epochs passes over tokens_per_epoch tokens. The -1 mirrors the
// reference's overlap accounting (ref: helpers.cpp:103,
// gpt_dataset.py:414-425): consecutive samples share one boundary token.
int64_t num_samples_from_epochs(int64_t num_epochs, int64_t tokens_per_epoch,
                                int32_t seq_length) {
  return (num_epochs * tokens_per_epoch - 1) / seq_length;
}

// Fill sample_idx[(num_samples+1) x 2] with (doc_idx_index, doc_offset)
// pairs: sample i spans tokens from pair i to pair i+1 inclusive.
// Parity: ref helpers.cpp build_sample_idx (:83-175) / the Python
// equivalent gpt_dataset.py:449-491.
void build_sample_idx(const int32_t* sizes, const int32_t* doc_idx,
                      int32_t seq_length, int64_t num_epochs,
                      int64_t tokens_per_epoch, int32_t* sample_idx) {
  const int64_t num_samples =
      num_samples_from_epochs(num_epochs, tokens_per_epoch, seq_length);

  int64_t doc_idx_index = 0;
  int32_t doc_offset = 0;
  sample_idx[0] = 0;
  sample_idx[1] = 0;

  for (int64_t s = 1; s <= num_samples; ++s) {
    int32_t remaining = seq_length + 1;
    while (remaining != 0) {
      const int32_t doc_length = sizes[doc_idx[doc_idx_index]] - doc_offset;
      remaining -= doc_length;
      if (remaining <= 0) {
        // sample ends inside this document; next sample re-reads the
        // boundary token (the -1)
        doc_offset += remaining + doc_length - 1;
        remaining = 0;
      } else {
        ++doc_idx_index;
        doc_offset = 0;
      }
    }
    sample_idx[2 * s] = static_cast<int32_t>(doc_idx_index);
    sample_idx[2 * s + 1] = doc_offset;
  }
}

// Greedy error-minimising interleave of weighted datasets.
// Parity: ref helpers.cpp build_blending_indices (:20-81) including the
// max(sample_idx, 1.0) detail so sample 0 matches.
void build_blending_indices(uint8_t* dataset_index,
                            int64_t* dataset_sample_index,
                            const double* weights, int32_t num_datasets,
                            int64_t size) {
  int64_t* current = new int64_t[num_datasets]();
  for (int64_t i = 0; i < size; ++i) {
    const double i_d = std::max(static_cast<double>(i), 1.0);
    int64_t best = 0;
    double best_err = weights[0] * i_d - static_cast<double>(current[0]);
    for (int32_t d = 1; d < num_datasets; ++d) {
      const double err = weights[d] * i_d - static_cast<double>(current[d]);
      if (err > best_err) {
        best_err = err;
        best = d;
      }
    }
    dataset_index[i] = static_cast<uint8_t>(best);
    dataset_sample_index[i] = current[best];
    ++current[best];
  }
  delete[] current;
}

// Sentence-pair sample map for BERT-style datasets: rows of
// (start_sentence, end_sentence, target_seq_length). Two-phase: with
// maps == nullptr only counts; with maps != nullptr fills and applies the
// seed+1 Fisher-Yates shuffle. RNG sequences use std::mt19937 exactly as
// the reference so the produced maps are bit-identical.
// Parity: ref helpers.cpp build_mapping_impl (:187-410).
int64_t build_mapping(const int64_t* docs, int64_t n_doc_bounds,
                      const int32_t* sizes, int32_t num_epochs,
                      uint64_t max_num_samples, int32_t max_seq_length,
                      double short_seq_prob, int32_t seed,
                      int32_t min_num_sent, int64_t* maps) {
  int32_t short_seq_ratio = 0;
  if (short_seq_prob > 0) {
    short_seq_ratio =
        static_cast<int32_t>(std::lround(1.0 / short_seq_prob));
  }
  const bool fill = maps != nullptr;
  std::mt19937 gen(seed);
  uint64_t map_index = 0;
  for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
    if (map_index >= max_num_samples) break;
    for (int64_t doc = 0; doc < n_doc_bounds - 1; ++doc) {
      const int64_t sent_first = docs[doc];
      const int64_t sent_last = docs[doc + 1];
      int64_t prev_start = sent_first;
      int64_t num_remain = sent_last - sent_first;

      bool has_long = false;
      if (num_remain > 1) {
        for (int64_t s = sent_first; s < sent_last; ++s) {
          if (sizes[s] > kLongSentenceLen) {
            has_long = true;
            break;
          }
        }
      }
      if (num_remain < min_num_sent || has_long) continue;

      int32_t seq_len = 0;
      int32_t num_sent = 0;
      int32_t target = target_sample_len(short_seq_ratio, max_seq_length, gen);
      for (int64_t s = sent_first; s < sent_last; ++s) {
        seq_len += sizes[s];
        ++num_sent;
        --num_remain;
        if (((seq_len >= target) && (num_remain > 1) &&
             (num_sent >= min_num_sent)) ||
            (num_remain == 0)) {
          if (fill) {
            maps[3 * map_index] = prev_start;
            maps[3 * map_index + 1] = s + 1;
            maps[3 * map_index + 2] = target;
          }
          ++map_index;
          prev_start = s + 1;
          target = target_sample_len(short_seq_ratio, max_seq_length, gen);
          seq_len = 0;
          num_sent = 0;
        }
      }
    }
  }
  if (fill) shuffle_rows(maps, static_cast<int64_t>(map_index), 3, seed);
  return static_cast<int64_t>(map_index);
}

// Sentence-block sample map for ICT/REALM-style datasets: rows of
// (start_sentence, end_sentence, doc_index, block_id). Same two-phase +
// shuffle contract as build_mapping.
// Parity: ref helpers.cpp build_blocks_mapping_impl (:453-656).
int64_t build_blocks_mapping(const int64_t* docs, int64_t n_doc_bounds,
                             const int32_t* sizes,
                             const int32_t* titles_sizes, int32_t num_epochs,
                             uint64_t max_num_samples, int32_t max_seq_length,
                             int32_t seed, int32_t use_one_sent_blocks,
                             int64_t* maps) {
  const int32_t min_num_sent = use_one_sent_blocks ? 1 : 2;
  const bool fill = maps != nullptr;
  uint64_t map_index = 0;
  for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
    int32_t block_id = 0;
    if (map_index >= max_num_samples) break;
    for (int64_t doc = 0; doc < n_doc_bounds - 1; ++doc) {
      const int64_t sent_first = docs[doc];
      const int64_t sent_last = docs[doc + 1];
      const int32_t target = max_seq_length - titles_sizes[doc];
      int64_t prev_start = sent_first;
      int64_t num_remain = sent_last - sent_first;

      bool has_long = false;
      if (num_remain >= min_num_sent) {
        for (int64_t s = sent_first; s < sent_last; ++s) {
          if (sizes[s] > kLongSentenceLen) {
            has_long = true;
            break;
          }
        }
      }
      if (num_remain < min_num_sent || has_long) continue;

      int32_t seq_len = 0;
      int32_t num_sent = 0;
      for (int64_t s = sent_first; s < sent_last; ++s) {
        seq_len += sizes[s];
        ++num_sent;
        --num_remain;
        if (((seq_len >= target) && (num_remain >= min_num_sent) &&
             (num_sent >= min_num_sent)) ||
            (num_remain == 0)) {
          if (fill) {
            maps[4 * map_index] = prev_start;
            maps[4 * map_index + 1] = s + 1;
            maps[4 * map_index + 2] = doc;
            maps[4 * map_index + 3] = block_id;
          }
          ++map_index;
          ++block_id;
          prev_start = s + 1;
          seq_len = 0;
          num_sent = 0;
        }
      }
    }
  }
  if (fill) shuffle_rows(maps, static_cast<int64_t>(map_index), 4, seed);
  return static_cast<int64_t>(map_index);
}

}  // extern "C"
