"""Self-contained GPT-2 byte-level BPE.

Functional parity with ref megatron/tokenizer/gpt2_tokenization.py (itself
the standard OpenAI GPT-2 encoder): byte-to-unicode mapping, greedy
lowest-rank pair merges, regex pre-tokenization. Loads the usual
vocab.json + merges.txt pair from local disk (no network).
"""

from __future__ import annotations

import json
from functools import lru_cache

try:  # the full GPT-2 split pattern needs the `regex` module
    import regex as _re

    _PAT = _re.compile(
        r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
    )
except ImportError:  # close approximation with stdlib re
    import re as _re

    _PAT = _re.compile(
        r"""'s|'t|'re|'ve|'m|'ll|'d| ?[A-Za-z]+| ?[0-9]+| ?[^\sA-Za-z0-9]+|\s+(?!\S)|\s+"""
    )


@lru_cache()
def bytes_to_unicode():
    """Invertible byte -> printable-unicode map (standard GPT-2 table)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word):
    pairs = set()
    prev = word[0]
    for ch in word[1:]:
        pairs.add((prev, ch))
        prev = ch
    return pairs


class GPT2BPE:
    def __init__(self, vocab_file: str, merges_file: str, errors: str = "replace"):
        with open(vocab_file, encoding="utf-8") as f:
            self.encoder = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.errors = errors
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        with open(merges_file, encoding="utf-8") as f:
            lines = f.read().split("\n")
        merges = [tuple(l.split()) for l in lines if l and not l.startswith("#version")]
        self.bpe_ranks = {m: i for i, m in enumerate(m for m in merges if len(m) == 2)}
        self.cache: dict = {}

    def bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word = tuple(token)
        pairs = _get_pairs(word)
        if not pairs:
            return token
        while True:
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i] == first and word[i + 1] == second:
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = " ".join(word)
        self.cache[token] = out
        return out

    def encode(self, text: str) -> list:
        ids = []
        for token in _PAT.findall(text):
            token = "".join(self.byte_encoder[b] for b in token.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self.bpe(token).split(" "))
        return ids

    def decode(self, ids) -> str:
        text = "".join(self.decoder[int(i)] for i in ids)
        return bytearray(self.byte_decoder[c] for c in text).decode(
            "utf-8", errors=self.errors
        )

    def __len__(self):
        return len(self.encoder)
