from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer  # noqa: F401
