"""Tokenizer registry + vocab padding.

Parity target: ref megatron/tokenizer/tokenizer.py:12-499 —
`build_tokenizer` dispatch, vocab padding to a multiple of
`make_vocab_size_divisible_by * tp` (:49-63), and the tokenizer classes:
BertWordPiece (:123), GPT2BPE (:254), Falcon/HF (:288), SentencePiece for
Llama incl. special + extra tokens (:326-404).

All tokenizers load from local files only (this image has zero egress).
SentencePiece is optional in the environment; the Llama path also accepts a
HF `tokenizer.json` via the `tokenizers` library.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional


def pad_vocab_size(orig_vocab_size: int, make_vocab_size_divisible_by: int,
                   tensor_parallel_size: int) -> int:
    """ref: _vocab_size_with_padding (tokenizer.py:49-63)."""
    after = orig_vocab_size
    multiple = make_vocab_size_divisible_by * tensor_parallel_size
    while after % multiple != 0:
        after += 1
    return after


class AbstractTokenizer(ABC):
    """ref: AbstractTokenizer (tokenizer.py:66-120)."""

    def __init__(self, name: str):
        self.name = name

    @property
    @abstractmethod
    def vocab_size(self) -> int: ...

    @property
    @abstractmethod
    def vocab(self) -> dict: ...

    @property
    @abstractmethod
    def inv_vocab(self) -> dict: ...

    @abstractmethod
    def tokenize(self, text: str) -> List[int]: ...

    def detokenize(self, token_ids) -> str:
        raise NotImplementedError(f"detokenizer not implemented for {self.name}")

    @property
    def cls(self):
        raise NotImplementedError

    @property
    def sep(self):
        raise NotImplementedError

    @property
    def pad(self):
        raise NotImplementedError

    @property
    def eod(self):
        raise NotImplementedError

    @property
    def mask(self):
        raise NotImplementedError


class _GPT2BPETokenizer(AbstractTokenizer):
    """ref: tokenizer.py:254-287."""

    def __init__(self, vocab_file: str, merges_file: str):
        super().__init__("GPT2 BPE")
        from megatron_llm_tpu.tokenizer.gpt2_bpe import GPT2BPE

        self.tokenizer = GPT2BPE(vocab_file, merges_file)
        self.eod_id = self.tokenizer.encoder["<|endoftext|>"]

    @property
    def vocab_size(self):
        return len(self.tokenizer.encoder)

    @property
    def vocab(self):
        return self.tokenizer.encoder

    @property
    def inv_vocab(self):
        return self.tokenizer.decoder

    def tokenize(self, text):
        return self.tokenizer.encode(text)

    def detokenize(self, token_ids):
        return self.tokenizer.decode(token_ids)

    @property
    def eod(self):
        return self.eod_id


class _SentencePieceTokenizer(AbstractTokenizer):
    """Llama tokenizer (ref: tokenizer.py:326-474): SentencePiece model +
    special tokens (<s>, </s>, [INST]... when vocab_extra_ids_list) and
    `new_tokens` gating."""

    def __init__(self, model_file: str, vocab_extra_ids_list: Optional[str] = None,
                 new_tokens: bool = True):
        super().__init__("SentencePieceTokenizer")
        import sentencepiece as spm  # optional dependency

        self.tokenizer = spm.SentencePieceProcessor(model_file=model_file)
        self._vocab = {self.tokenizer.id_to_piece(i): i
                       for i in range(self.tokenizer.get_piece_size())}
        self._inv_vocab = {i: p for p, i in self._vocab.items()}
        self._special_tokens = {}
        self._next_id = self.tokenizer.get_piece_size()
        if vocab_extra_ids_list and new_tokens:
            for tok in vocab_extra_ids_list.split(","):
                self._add_special(tok)

    def _add_special(self, tok: str):
        if tok not in self._vocab:
            self._vocab[tok] = self._next_id
            self._inv_vocab[self._next_id] = tok
            self._special_tokens[tok] = self._next_id
            self._next_id += 1

    @property
    def vocab_size(self):
        return self._next_id

    @property
    def vocab(self):
        return self._vocab

    @property
    def inv_vocab(self):
        return self._inv_vocab

    def tokenize(self, text):
        """Split on added special tokens first (ref: tokenizer.py:406-434 —
        the reference's added tokens are matched before SP encoding)."""
        if not self._special_tokens:
            return self.tokenizer.encode(text)
        ids: list = []
        rest = text
        specials = sorted(self._special_tokens, key=len, reverse=True)
        while rest:
            positions = [(rest.find(t), t) for t in specials if rest.find(t) >= 0]
            if not positions:
                ids.extend(self.tokenizer.encode(rest))
                break
            pos, tok = min(positions)
            if pos > 0:
                ids.extend(self.tokenizer.encode(rest[:pos]))
            ids.append(self._special_tokens[tok])
            rest = rest[pos + len(tok):]
        return ids

    def detokenize(self, token_ids):
        """Decode runs of SP ids, splicing added-special-token strings."""
        base = self.tokenizer.get_piece_size()
        out, run = [], []
        for t in (int(t) for t in token_ids):
            if t >= base:
                if run:
                    out.append(self.tokenizer.decode(run))
                    run = []
                out.append(self._inv_vocab[t])
            else:
                run.append(t)
        if run:
            out.append(self.tokenizer.decode(run))
        return "".join(out)

    @property
    def bos(self):
        return self.tokenizer.bos_id()

    @property
    def eos(self):
        return self.tokenizer.eos_id()

    @property
    def eod(self):
        return self.tokenizer.eos_id()

    @property
    def pad(self):
        return self.tokenizer.pad_id()


class _HFTokenizer(AbstractTokenizer):
    """HF tokenizers-backed wrapper (ref: _FalconTokenizer tokenizer.py:288-325
    uses transformers AutoTokenizer). Loads a local tokenizer.json or a
    local pretrained directory."""

    def __init__(self, path: str, name: str = "HFTokenizer"):
        super().__init__(name)
        import os

        if os.path.isdir(path):
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(path, local_files_only=True)
            self._encode = lambda t: self.tokenizer(t)["input_ids"]
            self._decode = self.tokenizer.decode
            self._size = len(self.tokenizer)
            self._vocab = self.tokenizer.get_vocab()
            self._eod = self.tokenizer.eos_token_id
        else:
            from tokenizers import Tokenizer

            self.tokenizer = Tokenizer.from_file(path)
            self._encode = lambda t: self.tokenizer.encode(t).ids
            self._decode = self.tokenizer.decode
            self._size = self.tokenizer.get_vocab_size()
            self._vocab = self.tokenizer.get_vocab()
            eos = None
            for cand in ("</s>", "<|endoftext|>", "<|end_of_text|>"):
                if cand in self._vocab:
                    eos = self._vocab[cand]
                    break
            self._eod = eos
        self._inv_vocab = {v: k for k, v in self._vocab.items()}

    @property
    def vocab_size(self):
        return self._size

    @property
    def vocab(self):
        return self._vocab

    @property
    def inv_vocab(self):
        return self._inv_vocab

    def tokenize(self, text):
        return self._encode(text)

    def detokenize(self, token_ids):
        return self._decode([int(t) for t in token_ids])

    @property
    def eod(self):
        return self._eod


class _FalconTokenizer(_HFTokenizer):
    """ref: tokenizer.py:288-325 (tiiuae/falcon HF tokenizer from local dir)."""

    def __init__(self, path: str):
        super().__init__(path, name="FalconTokenizer")


class _NullTokenizer(AbstractTokenizer):
    """Integer pass-through for pre-tokenized corpora and tests."""

    def __init__(self, vocab_size: int):
        super().__init__("NullTokenizer")
        self._size = int(vocab_size)

    @property
    def vocab_size(self):
        return self._size + 1  # +1 for eod

    @property
    def vocab(self):
        return {str(i): i for i in range(self.vocab_size)}

    @property
    def inv_vocab(self):
        return {i: str(i) for i in range(self.vocab_size)}

    def tokenize(self, text):
        return [int(t) for t in text.split()]

    def detokenize(self, token_ids):
        return " ".join(str(int(t)) for t in token_ids)

    @property
    def eod(self):
        return self._size


def build_tokenizer(
    tokenizer_type: str,
    vocab_file: Optional[str] = None,
    merges_file: Optional[str] = None,
    tokenizer_model: Optional[str] = None,
    make_vocab_size_divisible_by: int = 128,
    tensor_parallel_size: int = 1,
    vocab_extra_ids_list: Optional[str] = None,
    new_tokens: bool = True,
    null_vocab_size: Optional[int] = None,
    vocab_extra_ids: int = 0,
):
    """ref: build_tokenizer (tokenizer.py:12-47). Returns tokenizer with
    `padded_vocab_size` attribute set."""
    if tokenizer_type == "GPT2BPETokenizer":
        assert vocab_file and merges_file
        tokenizer = _GPT2BPETokenizer(vocab_file, merges_file)
    elif tokenizer_type == "SentencePieceTokenizer":
        assert tokenizer_model
        tokenizer = _SentencePieceTokenizer(
            tokenizer_model, vocab_extra_ids_list, new_tokens
        )
    elif tokenizer_type == "FalconTokenizer":
        tokenizer = _FalconTokenizer(tokenizer_model or vocab_file)
    elif tokenizer_type == "HFTokenizer":
        tokenizer = _HFTokenizer(tokenizer_model or vocab_file)
    elif tokenizer_type == "BertWordPieceLowerCase":
        tokenizer = _BertWordPieceTokenizer(vocab_file, lower_case=True,
                                            vocab_extra_ids=vocab_extra_ids)
    elif tokenizer_type == "BertWordPieceCase":
        tokenizer = _BertWordPieceTokenizer(vocab_file, lower_case=False,
                                            vocab_extra_ids=vocab_extra_ids)
    elif tokenizer_type == "NullTokenizer":
        tokenizer = _NullTokenizer(null_vocab_size or 0)
    else:
        raise NotImplementedError(f"{tokenizer_type} tokenizer is not implemented")

    tokenizer.padded_vocab_size = pad_vocab_size(
        tokenizer.vocab_size, make_vocab_size_divisible_by, tensor_parallel_size
    )
    return tokenizer


class _BertWordPieceTokenizer(AbstractTokenizer):
    """WordPiece tokenizer for BERT (ref: tokenizer.py:123-253 +
    bert_tokenization.py). Compact re-implementation: basic whitespace/punct
    split then greedy longest-match wordpieces."""

    def __init__(self, vocab_file: str, lower_case: bool = True,
                 vocab_extra_ids: int = 0):
        super().__init__(
            "BERT Lower Case" if lower_case else "BERT Upper Case"
        )
        self.lower_case = lower_case
        self._vocab = {}
        with open(vocab_file, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    self._vocab[tok] = i
        self.cls_id = self._vocab["[CLS]"]
        self.sep_id = self._vocab["[SEP]"]
        self.pad_id = self._vocab["[PAD]"]
        self.mask_id = self._vocab["[MASK]"]
        self.unk_id = self._vocab.get("[UNK]", 0)
        # [BOS]/[EOS] + <extra_id_N> sentinels for T5 span corruption
        # (ref: tokenizer.py:137-166)
        for tok in ("[BOS]", "[EOS]"):
            self._vocab.setdefault(tok, len(self._vocab))
        self._bos_token_id = self._vocab["[BOS]"]
        self._eos_token_id = self._vocab["[EOS]"]
        self._additional_special_tokens_ids = []
        for i in range(vocab_extra_ids):
            tok = f"<extra_id_{i}>"
            self._vocab.setdefault(tok, len(self._vocab))
            self._additional_special_tokens_ids.append(self._vocab[tok])
        self._inv = {v: k for k, v in self._vocab.items()}

    # -- basic tokenization ------------------------------------------------
    @staticmethod
    def _is_punct(ch):
        import unicodedata

        cp = ord(ch)
        if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
            return True
        return unicodedata.category(ch).startswith("P")

    def _basic_tokenize(self, text: str):
        if self.lower_case:
            text = text.lower()
        out, cur = [], []
        for ch in text:
            if ch.isspace():
                if cur:
                    out.append("".join(cur))
                    cur = []
            elif self._is_punct(ch):
                if cur:
                    out.append("".join(cur))
                    cur = []
                out.append(ch)
            else:
                cur.append(ch)
        if cur:
            out.append("".join(cur))
        return out

    def _wordpiece(self, word: str):
        if len(word) > 200:
            return [self.unk_id]
        pieces = []
        start = 0
        while start < len(word):
            end = len(word)
            cur_id = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self._vocab:
                    cur_id = self._vocab[sub]
                    break
                end -= 1
            if cur_id is None:
                return [self.unk_id]
            pieces.append(cur_id)
            start = end
        return pieces

    @property
    def vocab_size(self):
        return len(self._vocab)

    @property
    def vocab(self):
        return self._vocab

    @property
    def inv_vocab(self):
        return self._inv

    def tokenize(self, text):
        ids = []
        for word in self._basic_tokenize(text):
            ids.extend(self._wordpiece(word))
        return ids

    def detokenize(self, token_ids):
        toks = [self._inv[int(i)] for i in token_ids]
        out = []
        for t in toks:
            if t.startswith("##") and out:
                out[-1] = out[-1] + t[2:]
            else:
                out.append(t)
        return " ".join(out)

    @property
    def cls(self):
        return self.cls_id

    @property
    def sep(self):
        return self.sep_id

    @property
    def pad(self):
        return self.pad_id

    @property
    def mask(self):
        return self.mask_id

    @property
    def eod(self):
        return self.sep_id

    @property
    def bos_token_id(self):
        return self._bos_token_id

    @property
    def eos_token_id(self):
        return self._eos_token_id

    @property
    def additional_special_tokens_ids(self):
        return self._additional_special_tokens_ids
