"""CLI argument surface -> typed configs.

Parity target: ref megatron/arguments.py:14-1075 (17 groups, SURVEY.md
§2.5). The reference parses into one namespace consumed through a global;
here `parse_args` maps the same flag names onto (ModelConfig,
ParallelConfig, TrainConfig, data/tokenizer args) dataclasses. Flags keep
the reference spelling so shell scripts port unchanged.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import List, Optional

import jax.numpy as jnp

from megatron_llm_tpu.config import (
    ModelConfig,
    ParallelConfig,
    TrainConfig,
    falcon_config,
    gpt_config,
    llama_config,
)


@dataclass
class DataArgs:
    data_path: Optional[List[str]] = None
    # separate per-split corpora (ref: --train_data_path etc.,
    # gpt_dataset.py:78-128; mutually exclusive with data_path+split)
    train_data_path: Optional[List[str]] = None
    valid_data_path: Optional[List[str]] = None
    test_data_path: Optional[List[str]] = None
    split: str = "969,30,1"
    tokenizer_type: Optional[str] = None
    vocab_file: Optional[str] = None
    merges_file: Optional[str] = None
    tokenizer_model: Optional[str] = None
    vocab_extra_ids: int = 0
    vocab_extra_ids_list: Optional[str] = None
    new_tokens: bool = True
    seq_length: int = 2048
    reset_position_ids: bool = False
    reset_attention_mask: bool = False
    eod_mask_loss: bool = False
    null_vocab_size: Optional[int] = None
    dataloader_type: str = "single"


# ---------------------------------------------------------------------------
# Reference flag-surface audit tables (ref: megatron/arguments.py:406-1075).
# Every reference flag is in exactly one bucket: supported by this parser
# (possibly under an alias), handled by a specific entry script, SUBSUMED
# (accepted: the requested behavior is unconditionally provided by the TPU
# design, numerics unchanged), or DESCOPED (rejected loudly with the reason
# and the supported alternative). tests/test_flag_audit.py asserts the
# buckets cover the reference surface with zero silently-ignored flags.
# ---------------------------------------------------------------------------

SUBSUMED_FLAGS = {
    "--attention_softmax_in_fp32":
        "softmax statistics are always fp32 (models/attention.py, "
        "ops/flash_attention.py)",
    "--accumulate_allreduce_grads_in_fp32":
        "microbatch gradient accumulation is always fp32 "
        "(training/train_step.py)",
    "--data_impl":
        "one mmap-backed indexed-dataset implementation; "
        "'infer'/'mmap'/'lazy'/'cached' all map to it "
        "(data/indexed_dataset.py)",
    "--mmap_warmup":
        "mmap pages fault in on demand; no warmup pass needed",
    "--no_masked_softmax_fusion":
        "XLA fuses masked softmax automatically; no hand-written kernel "
        "to disable (numerics identical)",
    "--no_bias_gelu_fusion":
        "XLA fuses bias+gelu automatically (numerics identical)",
    "--no_bias_dropout_fusion":
        "XLA fuses bias+dropout automatically (numerics identical)",
    "--no_persist_layer_norm":
        "no persistent-kernel LayerNorm variant exists; XLA emits one "
        "fused norm",
    "--no_gradient_accumulation_fusion":
        "grad accumulation is one fused scan (training/train_step.py); "
        "no separate CUDA wgrad fusion to disable",
    "--no_async_tensor_model_parallel_allreduce":
        "GSPMD schedules TP collectives; there is no async/sync toggle",
    "--no_contiguous_buffers_in_local_ddp":
        "no DDP buffer management under GSPMD",
    "--empty_unused_memory_level":
        "XLA manages device memory; no allocator cache to empty",
    "--use_ring_exchange_p2p":
        "stage transfers are lax.ppermute - ring exchange IS the mechanism "
        "(parallel/pipeline.py)",
    "--distributed_backend":
        "collectives are XLA's over ICI/DCN; there is no backend choice",
    "--local_rank":
        "single-controller JAX; no per-rank launcher plumbing",
    "--use_cpu_initialization":
        "params are initialized under jit with sharded out_shardings - "
        "never materialized unsharded on one device (trainer.setup)",
    "--no_initialization":
        "param init is lazy under jit; converters never materialize "
        "random weights",
    "--no_query_key_layer_scaling":
        "query-key layer scaling is never applied (bf16 + fp32 softmax "
        "makes the fp16-overflow workaround unnecessary)",
    "--distribute_saved_activations":
        "jax.checkpoint + sequence-parallel sharding keep saved "
        "activations sharded by construction (tests/test_sp_memory.py)",
    "--no_scatter_gather_tensors_in_pipeline":
        "pipeline boundary tensors ride lax.ppermute; XLA picks layouts",
    "--num_workers":
        "synchronous single-controller host loader; no worker pool",
    "--no_save_rng":
        "no mutable RNG state is persisted; dropout keys derive from "
        "seed + iteration",
    "--log_batch_size_to_tensorboard":
        "batch-size is always written when tensorboard is enabled",
}

DESCOPED_FLAGS = {
    "--num_layers_per_virtual_pipeline_stage":
        "interleaved/virtual pipeline is unsupported by design: the "
        "per-tick-remat scan schedule makes num_microbatches the bubble "
        "lever (see ParallelConfig, docs/PIPELINE_MEMORY.md)",
    "--fp16_lm_cross_entropy":
        "cross-entropy is computed in fp32 (parallel/cross_entropy.py)",
    "--fp32_residual_connection":
        "the residual stream follows compute_dtype; fp32 residuals are "
        "descoped for bf16 training",
    "--apply_residual_connection_post_layernorm":
        "the residual-from-LN-output variant is unsupported; --use_post_ln "
        "provides the post-LN architecture (models/transformer.py)",
    "--init_method_xavier_uniform":
        "normal(--init_method_std) initialization only",
    "--encoder_num_layers":
        "asymmetric encoder/decoder depth is unsupported; --num_layers "
        "sets both T5 stacks",
    "--decoder_num_layers":
        "asymmetric encoder/decoder depth is unsupported; --num_layers "
        "sets both T5 stacks",
    "--pipeline_model_parallel_split_rank":
        "the scan pipeline shards the stacked layer axis uniformly; an "
        "encoder/decoder split rank has no analogue",
    "--standalone_embedding_stage":
        "embedding runs in-tick on every stage (parallel/pipeline.py); "
        "a dedicated embedding stage has no analogue",
    "--data_parallel_random_init":
        "dp replicas are one logical param tree under GSPMD; "
        "per-replica divergent init is not representable",
    "--adlr_autoresume":
        "use --autoresume_file (sentinel-file consensus exit, the TPU "
        "analogue of ADLR autoresume)",
    "--adlr_autoresume_interval":
        "use --autoresume_interval (see --adlr_autoresume)",
    "--head_lr_mult":
        "single LR group; per-head LR multipliers are descoped",
    "--max_tokens_to_oom":
        "generation buffers are fixed-shape at compile time; the "
        "runtime-OOM guard has no analogue",
    "--inference_batch_times_seqlen_threshold":
        "pp>1 serving dispatches on model size, not batch*seqlen (see "
        "inference/api.py)",
    "--onnx_safe":
        "no torch/ONNX export path; use tools/push_to_hub.py or "
        "convert/hf.py",
    "--no_data_sharding":
        "REALM/ICT index data machinery is descoped (legacy in the "
        "reference)",
}

# FP8 / TransformerEngine family — one shared reason.
for _f in ("--fp8_e4m3", "--fp8_hybrid", "--fp8_margin", "--fp8_interval",
           "--fp8_amax_history_len", "--fp8_amax_compute_algo",
           "--no_fp8_wgrad", "--transformer_impl"):
    DESCOPED_FLAGS[_f] = (
        "FP8/TransformerEngine path is descoped: no fp8 MXU on the "
        "current TPU target (bf16 is the training dtype)"
    )
# Vision model family — legacy in the reference.
for _f in ("--img_h", "--img_w", "--num_channels", "--num_classes",
           "--patch_dim", "--classes_fraction", "--data_per_class_fraction",
           "--iter_per_epoch", "--sample_rate", "--dino_local_img_size",
           "--dino_local_crops_number", "--dino_head_hidden_size",
           "--dino_bottleneck_size", "--dino_freeze_last_layer",
           "--dino_norm_last_layer", "--dino_warmup_teacher_temp",
           "--dino_teacher_temp", "--dino_warmup_teacher_temp_epochs"):
    DESCOPED_FLAGS[_f] = (
        "vision model family is descoped (legacy in the reference; see "
        "the README descope list)"
    )
# Residual REALM machinery — the embedding-index BUILD path is
# implemented (tools/build_retrieval_index.py + data/realm_index.py);
# these remaining knobs are legacy.
for _f in ("--bert_load", "--ict_load", "--ict_head_size",
           "--block_data_path", "--retriever_report_topk_accuracies",
           "--retriever_score_scaling"):
    DESCOPED_FLAGS[_f] = (
        "legacy REALM knob; the retrieval-index build path is "
        "tools/build_retrieval_index.py (--embedding_path/--indexer_*) "
        "and ORQA eval lives under tasks/"
    )

# Reference flags owned by a specific entry script's parser rather than the
# base parser (the reference keeps ALL flags global; here task-family knobs
# live with the script that consumes them).
ENTRY_SCRIPT_FLAGS = {
    "--mask_prob": ("pretrain_bert.py", "pretrain_t5.py"),
    "--short_seq_prob": ("pretrain_bert.py", "pretrain_t5.py"),
    "--decoder_seq_length": ("pretrain_t5.py",),
    "--titles_data_path": ("pretrain_ict.py",),
    "--query_in_block_prob": ("pretrain_ict.py",),
    "--use_one_sent_docs": ("pretrain_ict.py",),
    "--biencoder_projection_dim": ("pretrain_ict.py", "tasks/main.py"),
    "--biencoder_shared_query_context_model": ("pretrain_ict.py",
                                               "tasks/main.py"),
    "--evidence_data_path": ("tasks/main.py",
                             "tools/build_retrieval_index.py"),
    "--embedding_path": ("tasks/main.py",
                         "tools/build_retrieval_index.py"),
    "--indexer_batch_size": ("tools/build_retrieval_index.py",),
    "--indexer_log_interval": ("tools/build_retrieval_index.py",),
    "--retriever_seq_length": ("tasks/main.py",
                               "tools/build_retrieval_index.py"),
}


def build_base_parser() -> argparse.ArgumentParser:
    """ref: build_base_parser (arguments.py:14-34)."""
    p = argparse.ArgumentParser(description="megatron_llm_tpu arguments",
                                allow_abbrev=False)
    g = p.add_argument_group("network size")  # ref :406-474
    g.add_argument("--model_name", default="gpt",
                   choices=["gpt", "llama", "llama2", "codellama", "falcon",
                            "bert", "t5"])
    g.add_argument("--model_size", type=int, default=7)
    g.add_argument("--num_layers", type=int, default=None)
    g.add_argument("--hidden_size", type=int, default=None)
    g.add_argument("--ffn_hidden_size", type=int, default=None)
    g.add_argument("--num_attention_heads", type=int, default=None)
    g.add_argument("--num_attention_heads_kv", type=int, default=None)
    g.add_argument("--kv_channels", type=int, default=None)
    g.add_argument("--max_position_embeddings", type=int, default=None)
    g.add_argument("--make_vocab_size_divisible_by", type=int, default=128)
    g.add_argument("--layernorm_epsilon", type=float, default=None)
    g.add_argument("--init_method_std", type=float, default=None)
    g.add_argument("--use_bias", action="store_true", default=None)
    g.add_argument("--use_rms_norm", action="store_true", default=None)
    g.add_argument("--use_post_ln", action="store_true", default=None)
    g.add_argument("--glu_activation", type=str, default=None)
    g.add_argument("--position_embedding_type", type=str, default=None)
    g.add_argument("--rope_scaling_factor", type=float, default=None,
                   help="linear RoPE position interpolation divisor "
                        "(positions / factor before rotation; 1.0 = off)")
    g.add_argument("--rope_theta", type=float, default=None,
                   help="rotary base frequency (default 10000; long-"
                        "context finetunes commonly raise it, e.g. 1e6)")
    g.add_argument("--attention_window_size", type=int, default=None,
                   help="sliding-window attention reach in tokens for "
                        "the serving-side paged kernels (training paths "
                        "ignore it; None = full causal)")
    g.add_argument("--parallel_attn", action="store_true", default=None)
    g.add_argument("--parallel_layernorm", action="store_true", default=None)
    g.add_argument("--no_tie_embed_logits", action="store_true")

    g = p.add_argument_group("regularization")  # ref :544-574
    g.add_argument("--hidden_dropout", type=float, default=None)
    g.add_argument("--attention_dropout", type=float, default=None)
    g.add_argument("--lima_dropout", action="store_true", default=None)
    g.add_argument("--weight_decay", type=float, default=0.01)
    g.add_argument("--start_weight_decay", type=float, default=None)
    g.add_argument("--end_weight_decay", type=float, default=None)
    g.add_argument("--weight_decay_incr_style", default="constant")
    g.add_argument("--clip_grad", type=float, default=1.0)
    g.add_argument("--adam_beta1", type=float, default=0.9)
    g.add_argument("--adam_beta2", type=float, default=0.999)
    g.add_argument("--adam_eps", type=float, default=1e-8)
    g.add_argument("--sgd_momentum", type=float, default=0.9)

    g = p.add_argument_group("training")  # ref :579-691
    g.add_argument("--micro_batch_size", type=int, default=1)
    g.add_argument("--global_batch_size", type=int, default=None)
    g.add_argument("--rampup_batch_size", nargs=3, type=int, default=None)
    g.add_argument("--train_iters", type=int, default=None)
    # sample-based duration (ref: --train_samples arguments.py:585; the
    # scheduler then steps in consumed samples, not iterations)
    g.add_argument("--train_samples", type=int, default=None)
    g.add_argument("--exit_interval", type=int, default=None)
    g.add_argument("--exit_duration_in_mins", type=float, default=None)
    g.add_argument("--exit_signal_handler", action="store_true")
    # sentinel-file autoresume (TPU analogue of ref --adlr_autoresume)
    g.add_argument("--autoresume_file", type=str, default=None)
    g.add_argument("--autoresume_interval", type=int, default=50)
    g.add_argument("--optimizer", default="adam", choices=["adam", "sgd"])
    g.add_argument("--dataloader_type", default="single",
                   choices=["single", "cyclic"])
    g.add_argument("--use_flash_attn", action="store_true", default=None)
    # Llama presets default flash ON; this is the CLI off-switch.
    g.add_argument("--no_use_flash_attn", dest="use_flash_attn",
                   action="store_false")
    g.add_argument("--recompute_granularity", default=None,
                   choices=[None, "full", "selective"])
    # ref: --recompute_activations is shorthand for selective granularity
    # (arguments.py:649-652)
    g.add_argument("--recompute_activations", action="store_true")
    # first-class remat-policy name (ModelConfig.remat_policy /
    # models/remat.py): the named-savepoint ladder. Give this OR the
    # --recompute_* reference spellings; inconsistent combinations raise
    # at config validation (ModelConfig.__post_init__), never train wrong.
    g.add_argument("--remat_policy", default=None,
                   choices=[None, "full", "selective", "save_dots",
                            "offload", "none"])
    # ref: --recompute_method/--recompute_num_layers (arguments.py:616-630)
    # — "block" remats only the first N scanned layers (the split-scan
    # path in models/transformer.py), composing with any remat policy
    g.add_argument("--recompute_method", default=None,
                   choices=[None, "uniform", "block"])
    g.add_argument("--recompute_num_layers", type=int, default=None)
    g.add_argument("--sequence_parallel", action="store_true")

    g = p.add_argument_group("learning rate")  # ref :710-747
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--lr_decay_style", default="linear",
                   choices=["constant", "linear", "cosine", "inverse-square-root"])
    g.add_argument("--lr_decay_iters", type=int, default=None)
    g.add_argument("--lr_decay_samples", type=int, default=None)
    g.add_argument("--lr_warmup_fraction", type=float, default=None)
    g.add_argument("--lr_warmup_iters", type=int, default=0)
    g.add_argument("--lr_warmup_samples", type=int, default=0)
    g.add_argument("--min_lr", type=float, default=0.0)
    g.add_argument("--use_checkpoint_opt_param_scheduler", action="store_true")
    g.add_argument("--override_opt_param_scheduler", action="store_true")

    g = p.add_argument_group("checkpointing")  # ref :751-779
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save_interval", type=int, default=None)
    g.add_argument("--load", type=str, default=None)
    # ref: --use_checkpoint_args (checkpointing.py:476 load_args_from_
    # checkpoint): take the model architecture from the checkpoint's meta
    g.add_argument("--use_checkpoint_args", action="store_true")
    g.add_argument("--finetune", action="store_true")
    g.add_argument("--no_save_optim", action="store_true")
    g.add_argument("--no_load_optim", action="store_true")
    g.add_argument("--no_load_rng", action="store_true")
    # fault tolerance (ISSUE 5, training/checkpointing.py CheckpointManager
    # + training/watchdog.py; docs/GUIDE.md "Fault tolerance")
    g.add_argument("--no_async_save", dest="async_save",
                   action="store_false", default=True,
                   help="block the train loop until each checkpoint is "
                        "fully committed (default: async — the loop only "
                        "pays the device→host copy)")
    g.add_argument("--keep_latest_n", type=int, default=None,
                   help="retention GC: keep only the newest N complete "
                        "checkpoints (default: keep everything)")
    g.add_argument("--loss_watchdog_ksigma", type=float, default=0.0,
                   help="skip optimizer updates whose loss exceeds "
                        "median + k*sigma of the recent-loss window "
                        "(robust MAD sigma); 0 disables spike detection")
    g.add_argument("--loss_watchdog_window", type=int, default=64)
    g.add_argument("--spike_rollback_patience", type=int, default=0,
                   help="after N consecutive bad steps, reload the last "
                        "complete checkpoint and fast-forward the data "
                        "iterator past the poison window; 0 disables")

    g = p.add_argument_group("mixed precision")  # ref :783-815
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss_scale", type=float, default=None)
    g.add_argument("--initial_loss_scale", type=float, default=2.0**32)
    g.add_argument("--min_loss_scale", type=float, default=1.0)
    g.add_argument("--loss_scale_window", type=int, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)

    g = p.add_argument_group("distributed")  # ref :820-866
    g.add_argument("--tensor_model_parallel_size", type=int, default=1)
    g.add_argument("--pipeline_model_parallel_size", type=int, default=1)
    # --num_layers_per_virtual_pipeline_stage is rejected via
    # DESCOPED_FLAGS (registered below) so reference scripts fail loudly.
    g.add_argument("--use_distributed_optimizer", action="store_true")
    # ZeRO-1 explicit-decomposition knobs (ISSUE 10, optimizer/zero1.py):
    # reduce-scatter bucket size target (MB of fp32 grad payload per
    # collective) and the opt-in EQuARX-style int8 gradient reduction
    # (pure-dp meshes; default OFF, fp path bitwise-unchanged)
    g.add_argument("--grad_rs_bucket_mb", type=float, default=4.0)
    g.add_argument("--quantized_grad_reduce", action="store_true")
    # collective overlap scheduling (ISSUE 12, docs/GUIDE.md
    # "Collective overlap scheduling"): backward-interleaved grad
    # reduce-scatter, per-bucket first-needed param all-gather, and the
    # pp stage-ring's async double-buffered tick dispatch. All default
    # OFF — the eager schedules stay the bitwise oracles.
    g.add_argument("--overlap_grad_reduce", action="store_true")
    g.add_argument("--overlap_param_gather", action="store_true")
    g.add_argument("--async_pipeline_dispatch", action="store_true")
    g.add_argument("--data_parallel_size", type=int, default=None)
    # context parallelism (ring attention over the sequence axis) — a
    # beyond-reference long-context axis; see ParallelConfig.
    g.add_argument("--context_parallel_size", type=int, default=1)
    # pipeline backward remat policy (see ParallelConfig.pipeline_remat) —
    # the shared models/remat.py vocabulary plus the legacy tick/dots
    # aliases; "none"/"dots"/"selective" give 1F1B-class FLOPs when
    # per-stage HBM allows
    g.add_argument("--pipeline_remat", default="tick",
                   choices=["tick", "full", "selective", "dots",
                            "save_dots", "offload", "none"])

    g = p.add_argument_group("validation")  # ref :870-877
    g.add_argument("--eval_iters", type=int, default=100)
    g.add_argument("--eval_interval", type=int, default=1000)

    g = p.add_argument_group("data")  # ref :881-962
    g.add_argument("--data_path", nargs="*", default=None)
    # separate per-split corpora (ref: gpt_dataset.py:78-128)
    g.add_argument("--train_data_path", nargs="*", default=None)
    g.add_argument("--valid_data_path", nargs="*", default=None)
    g.add_argument("--test_data_path", nargs="*", default=None)
    g.add_argument("--split", default="969,30,1")
    # --encoder_seq_length is the reference's T5 spelling of the same knob
    # (validate_args maps seq_length = encoder_seq_length)
    g.add_argument("--seq_length", "--encoder_seq_length", type=int,
                   default=2048)
    g.add_argument("--tokenizer_type", type=str, default=None)
    g.add_argument("--vocab_file", type=str, default=None)
    # --merge_file is the reference spelling (arguments.py:898)
    g.add_argument("--merges_file", "--merge_file", type=str, default=None)
    g.add_argument("--tokenizer_model", type=str, default=None)
    # sentinel/extra tokens (ref: arguments.py:913-917, :950; consumed by
    # build_tokenizer)
    g.add_argument("--vocab_extra_ids", type=int, default=0)
    g.add_argument("--vocab_extra_ids_list", type=str, default=None)
    g.add_argument("--no_new_tokens", dest="new_tokens",
                   action="store_false")
    g.add_argument("--null_vocab_size", type=int, default=None)
    g.add_argument("--reset_position_ids", action="store_true")
    g.add_argument("--reset_attention_mask", action="store_true")
    g.add_argument("--eod_mask_loss", action="store_true")
    g.add_argument("--seed", type=int, default=1234)

    g = p.add_argument_group("logging")  # ref :477-541
    g.add_argument("--log_interval", type=int, default=100)
    g.add_argument("--tensorboard_dir", type=str, default=None)
    g.add_argument("--tensorboard_log_interval", type=int, default=1)
    g.add_argument("--tensorboard_queue_size", type=int, default=1000)
    g.add_argument("--log_timers_to_tensorboard", action="store_true")
    g.add_argument("--log_validation_ppl_to_tensorboard",
                   action="store_true")
    g.add_argument("--log_memory_to_tensorboard", action="store_true")
    g.add_argument("--log_world_size_to_tensorboard", action="store_true")
    g.add_argument("--timing_log_level", type=int, default=0,
                   choices=[0, 1, 2])
    g.add_argument("--timing_log_option", default="minmax",
                   choices=["max", "minmax", "all"])
    g.add_argument("--wandb_logger", action="store_true")
    g.add_argument("--wandb_project", type=str, default=None)
    g.add_argument("--wandb_entity", type=str, default=None)
    g.add_argument("--wandb_id", type=str, default=None)
    g.add_argument("--wandb_resume", action="store_true")
    g.add_argument("--wandb_api_key", type=str, default=None)
    g.add_argument("--log_params_norm", action="store_true")
    g.add_argument("--log_num_zeros_in_grad", action="store_true")
    g.add_argument("--profile", action="store_true")
    g.add_argument("--profile_step_start", type=int, default=10)
    g.add_argument("--profile_step_end", type=int, default=12)
    g.add_argument("--profile_dir", type=str, default=None)
    # flight-recorder telemetry (ISSUE 13, megatron_llm_tpu/telemetry/;
    # docs/GUIDE.md "Observability")
    g.add_argument("--profile_step_range", nargs=2, type=int, default=None,
                   metavar=("START", "END"),
                   help="shorthand for --profile --profile_step_start "
                        "START --profile_step_end END: capture a "
                        "jax.profiler device trace over [START, END)")
    g.add_argument("--trace_dir", type=str, default=None,
                   help="enable the host span tracer; the Chrome "
                        "trace-event JSON (Perfetto-loadable) exports "
                        "here at the end of training")
    g.add_argument("--flight_record_dir", type=str, default=None,
                   help="where flight-recorder crash artifacts are "
                        "dumped (watchdog rollback, SIGTERM emergency "
                        "save); default: the --save dir")
    g.add_argument("--flight_recorder_size", type=int, default=4096,
                   help="bounded ring of recent structured events the "
                        "flight recorder keeps (per-step/lifecycle; "
                        "the crash artifact's history depth)")
    # goodput & device-cost accounting (ISSUE 15, telemetry/chipspec +
    # costs + goodput + sentinel; docs/GUIDE.md "Goodput & device-cost
    # accounting"). The goodput ledger itself is always on.
    g.add_argument("--device_cost_registry", action="store_true",
                   help="capture each train-step specialization's "
                        "compiled cost (cost_analysis FLOPs/bytes + "
                        "memory_analysis temp/args) at mint time into "
                        "the CostRegistry: upgrades the live MFU gauge "
                        "from the analytic 6N model to registry FLOPs "
                        "and adds the per-executable achieved-GB/s "
                        "roofline gauge. Costs one extra AOT compile "
                        "per step specialization")
    g.add_argument("--chip_spec", type=str, default=None,
                   choices=["v5e", "v5p", "v4"],
                   help="override TPU-generation detection for the "
                        "MFU/roofline denominators (telemetry/"
                        "chipspec.py table; default: detect from "
                        "jax.devices(), gauges absent when unknown)")
    g.add_argument("--perf_sentinel_ksigma", type=float, default=0.0,
                   help="arm the step-latency perf-regression "
                        "sentinel: a step_ms above median + ksigma * "
                        "1.4826*MAD of the recent window is bad; "
                        "patience consecutive bad steps trip it — "
                        "flight-recorder trail + ring auto-dump, the "
                        "watchdog's postmortem path. 0 disables "
                        "(default)")
    g.add_argument("--perf_sentinel_window", type=int, default=64,
                   help="sliding window of good step_ms samples the "
                        "sentinel's median+MAD baseline is computed "
                        "over")
    g.add_argument("--perf_sentinel_patience", type=int, default=8,
                   help="consecutive bad steps that escalate to a "
                        "sentinel trip (ring auto-dump + counter)")

    # reference flags whose behavior is unconditionally provided (accepted,
    # recorded) or descoped (rejected in args_to_configs with the reason).
    # nargs="*" absorbs both `--flag` and `--flag value ...` spellings.
    for flag in SUBSUMED_FLAGS:
        p.add_argument(flag, nargs="*", default=None, help=argparse.SUPPRESS,
                       dest="_subsumed_" + flag.lstrip("-"))
    for flag in DESCOPED_FLAGS:
        p.add_argument(flag, nargs="*", default=None, help=argparse.SUPPRESS,
                       dest="_descoped_" + flag.lstrip("-"))

    return p


def args_to_configs(args, padded_vocab_size: int):
    """Map the parsed namespace onto typed configs (the reference's
    validate_args derivations, arguments.py:52-345)."""
    tp = args.tensor_model_parallel_size
    pp = args.pipeline_model_parallel_size
    # descoped reference flags fail loudly with the reason; subsumed ones
    # are acknowledged on stderr (the behavior is already unconditionally
    # provided — see the tables above)
    for flag, reason in DESCOPED_FLAGS.items():
        if getattr(args, "_descoped_" + flag.lstrip("-"), None) is not None:
            raise SystemExit(f"{flag}: unsupported — {reason}")
    for flag, reason in SUBSUMED_FLAGS.items():
        if getattr(args, "_subsumed_" + flag.lstrip("-"), None) is not None:
            import sys as _sys

            print(f"note: {flag} accepted; {reason}", file=_sys.stderr)

    if args.recompute_activations and args.recompute_granularity is None:
        # ref shorthand (arguments.py:649-652)
        args.recompute_granularity = "selective"

    if args.profile_step_range is not None:
        start, end = args.profile_step_range
        if start < 0 or end <= start:
            raise SystemExit(
                f"--profile_step_range {start} {end}: requires "
                f"0 <= START < END (the capture window is [START, END))")

    if args.data_path and (args.train_data_path or args.valid_data_path
                           or args.test_data_path):
        # the reference errors on this combination too
        # (gpt_dataset.py:31 vs :78 — one or the other)
        raise SystemExit(
            "--data_path and --train_data_path/--valid_data_path/"
            "--test_data_path are mutually exclusive"
        )

    overrides = {}
    for name in (
        "num_layers", "hidden_size", "ffn_hidden_size", "num_attention_heads",
        "num_attention_heads_kv", "kv_channels", "layernorm_epsilon",
        "init_method_std",
        "glu_activation", "position_embedding_type", "rope_scaling_factor",
        "rope_theta", "attention_window_size",
        "hidden_dropout", "attention_dropout", "lima_dropout",
        "use_flash_attn", "recompute_granularity", "remat_policy",
        "recompute_method", "recompute_num_layers", "use_bias",
        "use_rms_norm", "use_post_ln", "parallel_attn", "parallel_layernorm",
    ):
        v = getattr(args, name)
        if v is not None:
            overrides[name] = v
    if args.max_position_embeddings is not None:
        overrides["max_position_embeddings"] = args.max_position_embeddings
    else:
        overrides["max_position_embeddings"] = args.seq_length
    overrides["make_vocab_size_divisible_by"] = args.make_vocab_size_divisible_by
    if args.no_tie_embed_logits:
        overrides["tie_embed_logits"] = False
    if args.fp16:
        overrides["params_dtype"] = jnp.float32
        overrides["compute_dtype"] = jnp.float16

    name = args.model_name
    if name in ("llama", "llama2"):
        mcfg = llama_config(args.model_size, version=1 if name == "llama" else 2,
                            seq_length=args.seq_length, tp=tp, **overrides)
    elif name == "codellama":
        from megatron_llm_tpu.config import codellama_config

        mcfg = codellama_config(args.model_size, seq_length=args.seq_length,
                                **overrides)
    elif name == "falcon":
        mcfg = falcon_config(args.model_size, seq_length=args.seq_length,
                             tp=tp, **overrides)
    elif name in ("bert", "t5"):
        from megatron_llm_tpu.config import bert_config, t5_config

        preset = bert_config if name == "bert" else t5_config
        mcfg = preset(
            num_layers=overrides.pop("num_layers", 12),
            hidden_size=overrides.pop("hidden_size", 768),
            num_attention_heads=overrides.pop("num_attention_heads", 12),
            seq_length=args.seq_length,
            tp=tp,
            **overrides,
        )
    else:
        mcfg = gpt_config(
            num_layers=overrides.pop("num_layers", 12),
            hidden_size=overrides.pop("hidden_size", 768),
            num_attention_heads=overrides.pop("num_attention_heads", 12),
            seq_length=args.seq_length,
            tp=tp,
            **overrides,
        )
    import dataclasses as _dc

    mcfg = _dc.replace(mcfg, padded_vocab_size=mcfg.pad_vocab_size(
        padded_vocab_size, tp) if padded_vocab_size else mcfg.padded_vocab_size)

    import jax

    cp = getattr(args, "context_parallel_size", 1) or 1
    if cp > 1 and name in ("bert", "t5"):
        # ADVICE r5 carry-forward: BERT/T5 padding masks are dense
        # (b, 1, s, s) tensors with no packed-document {'doc_start'}
        # equivalent, and ring attention (the only cp>1 attention path)
        # cannot serve a dense mask. The old behavior dead-ended
        # MID-FORWARD (models/attention.py raises on the first masked
        # layer) — reject HERE, at config construction, with the
        # alternatives instead.
        raise SystemExit(
            f"--context_parallel_size {cp} with --model_name {name}: "
            "BERT/T5-style padding masks are dense attention masks, "
            "which context parallelism cannot shard (ring attention has "
            "no dense-mask path, and a gathered fallback would silently "
            "lose the memory scaling cp exists for). Use "
            "--context_parallel_size 1 for this model family, or move "
            "the parallelism to --tensor_model_parallel_size / "
            "--pipeline_model_parallel_size / data parallel "
            "(docs/GUIDE.md, 'Masks')."
        )
    dp = args.data_parallel_size
    if dp is None:
        dp = max(1, len(jax.devices()) // (tp * pp * cp))
    gbs = args.global_batch_size or args.micro_batch_size * dp
    num_micro = gbs // (args.micro_batch_size * dp)
    pcfg = ParallelConfig(
        data_parallel_size=dp,
        pipeline_parallel_size=pp,
        tensor_parallel_size=tp,
        context_parallel_size=cp,
        sequence_parallel=args.sequence_parallel,
        use_distributed_optimizer=args.use_distributed_optimizer,
        grad_rs_bucket_mb=args.grad_rs_bucket_mb,
        quantized_grad_reduce=args.quantized_grad_reduce,
        overlap_grad_reduce=args.overlap_grad_reduce,
        overlap_param_gather=args.overlap_param_gather,
        async_pipeline_dispatch=args.async_pipeline_dispatch,
        num_microbatches=num_micro,
        pipeline_remat=args.pipeline_remat,
    )

    tcfg = TrainConfig(
        micro_batch_size=args.micro_batch_size,
        global_batch_size=gbs,
        rampup_batch_size=tuple(args.rampup_batch_size)
        if args.rampup_batch_size else None,
        train_iters=args.train_iters,
        train_samples=args.train_samples,
        exit_interval=args.exit_interval,
        exit_duration_in_mins=args.exit_duration_in_mins,
        exit_signal_handler=args.exit_signal_handler,
        autoresume_file=args.autoresume_file,
        autoresume_interval=args.autoresume_interval,
        optimizer=args.optimizer,
        lr=args.lr,
        min_lr=args.min_lr,
        lr_decay_style=args.lr_decay_style,
        lr_decay_iters=args.lr_decay_iters,
        lr_decay_samples=args.lr_decay_samples,
        lr_warmup_iters=args.lr_warmup_iters,
        lr_warmup_samples=args.lr_warmup_samples,
        lr_warmup_fraction=args.lr_warmup_fraction,
        use_checkpoint_opt_param_scheduler=args.use_checkpoint_opt_param_scheduler,
        override_opt_param_scheduler=args.override_opt_param_scheduler,
        weight_decay=args.weight_decay,
        start_weight_decay=args.start_weight_decay,
        end_weight_decay=args.end_weight_decay,
        weight_decay_incr_style=args.weight_decay_incr_style,
        clip_grad=args.clip_grad,
        adam_beta1=args.adam_beta1,
        adam_beta2=args.adam_beta2,
        adam_eps=args.adam_eps,
        sgd_momentum=args.sgd_momentum,
        fp16=args.fp16,
        # --bf16 --fp16 together must trip the exclusivity check
        bf16=args.bf16 or not args.fp16,
        loss_scale=args.loss_scale,
        initial_loss_scale=args.initial_loss_scale,
        min_loss_scale=args.min_loss_scale,
        loss_scale_window=args.loss_scale_window,
        hysteresis=args.hysteresis,
        save=args.save,
        load=args.load,
        save_interval=args.save_interval,
        finetune=args.finetune,
        no_save_optim=args.no_save_optim,
        no_load_optim=args.no_load_optim,
        no_load_rng=args.no_load_rng,
        async_save=args.async_save,
        keep_latest_n=args.keep_latest_n,
        loss_watchdog_ksigma=args.loss_watchdog_ksigma,
        loss_watchdog_window=args.loss_watchdog_window,
        spike_rollback_patience=args.spike_rollback_patience,
        log_interval=args.log_interval,
        eval_interval=args.eval_interval,
        eval_iters=args.eval_iters,
        tensorboard_dir=args.tensorboard_dir,
        tensorboard_log_interval=args.tensorboard_log_interval,
        tensorboard_queue_size=args.tensorboard_queue_size,
        log_timers_to_tensorboard=args.log_timers_to_tensorboard,
        log_validation_ppl_to_tensorboard=args.log_validation_ppl_to_tensorboard,
        log_memory_to_tensorboard=args.log_memory_to_tensorboard,
        log_world_size_to_tensorboard=args.log_world_size_to_tensorboard,
        timing_log_level=args.timing_log_level,
        timing_log_option=args.timing_log_option,
        wandb_logger=args.wandb_logger,
        wandb_project=args.wandb_project,
        wandb_entity=args.wandb_entity,
        wandb_id=args.wandb_id,
        wandb_resume=args.wandb_resume,
        wandb_api_key=args.wandb_api_key,
        log_params_norm=args.log_params_norm,
        log_num_zeros_in_grad=args.log_num_zeros_in_grad,
        profile=args.profile or args.profile_step_range is not None,
        profile_step_start=(args.profile_step_range[0]
                            if args.profile_step_range is not None
                            else args.profile_step_start),
        profile_step_end=(args.profile_step_range[1]
                          if args.profile_step_range is not None
                          else args.profile_step_end),
        profile_dir=args.profile_dir,
        trace_dir=args.trace_dir,
        flight_record_dir=args.flight_record_dir,
        flight_recorder_size=args.flight_recorder_size,
        device_cost_registry=args.device_cost_registry,
        chip_spec=args.chip_spec,
        perf_sentinel_ksigma=args.perf_sentinel_ksigma,
        perf_sentinel_window=args.perf_sentinel_window,
        perf_sentinel_patience=args.perf_sentinel_patience,
        seed=args.seed,
    )

    dargs = DataArgs(
        data_path=args.data_path,
        train_data_path=args.train_data_path,
        valid_data_path=args.valid_data_path,
        test_data_path=args.test_data_path,
        split=args.split,
        tokenizer_type=args.tokenizer_type,
        vocab_file=args.vocab_file,
        merges_file=args.merges_file,
        tokenizer_model=args.tokenizer_model,
        vocab_extra_ids=args.vocab_extra_ids,
        vocab_extra_ids_list=args.vocab_extra_ids_list,
        new_tokens=args.new_tokens,
        seq_length=args.seq_length,
        reset_position_ids=args.reset_position_ids,
        reset_attention_mask=args.reset_attention_mask,
        eod_mask_loss=args.eod_mask_loss,
        null_vocab_size=args.null_vocab_size,
        dataloader_type=args.dataloader_type,
    )
    return mcfg, pcfg, tcfg, dargs
