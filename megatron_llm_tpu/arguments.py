"""CLI argument surface -> typed configs.

Parity target: ref megatron/arguments.py:14-1075 (17 groups, SURVEY.md
§2.5). The reference parses into one namespace consumed through a global;
here `parse_args` maps the same flag names onto (ModelConfig,
ParallelConfig, TrainConfig, data/tokenizer args) dataclasses. Flags keep
the reference spelling so shell scripts port unchanged.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import List, Optional

import jax.numpy as jnp

from megatron_llm_tpu.config import (
    ModelConfig,
    ParallelConfig,
    TrainConfig,
    falcon_config,
    gpt_config,
    llama_config,
)


@dataclass
class DataArgs:
    data_path: Optional[List[str]] = None
    split: str = "969,30,1"
    tokenizer_type: Optional[str] = None
    vocab_file: Optional[str] = None
    merges_file: Optional[str] = None
    tokenizer_model: Optional[str] = None
    seq_length: int = 2048
    reset_position_ids: bool = False
    reset_attention_mask: bool = False
    eod_mask_loss: bool = False
    null_vocab_size: Optional[int] = None
    dataloader_type: str = "single"


def build_base_parser() -> argparse.ArgumentParser:
    """ref: build_base_parser (arguments.py:14-34)."""
    p = argparse.ArgumentParser(description="megatron_llm_tpu arguments",
                                allow_abbrev=False)
    g = p.add_argument_group("network size")  # ref :406-474
    g.add_argument("--model_name", default="gpt",
                   choices=["gpt", "llama", "llama2", "codellama", "falcon",
                            "bert", "t5"])
    g.add_argument("--model_size", type=int, default=7)
    g.add_argument("--num_layers", type=int, default=None)
    g.add_argument("--hidden_size", type=int, default=None)
    g.add_argument("--ffn_hidden_size", type=int, default=None)
    g.add_argument("--num_attention_heads", type=int, default=None)
    g.add_argument("--num_attention_heads_kv", type=int, default=None)
    g.add_argument("--kv_channels", type=int, default=None)
    g.add_argument("--max_position_embeddings", type=int, default=None)
    g.add_argument("--make_vocab_size_divisible_by", type=int, default=128)
    g.add_argument("--layernorm_epsilon", type=float, default=None)
    g.add_argument("--use_bias", action="store_true", default=None)
    g.add_argument("--use_rms_norm", action="store_true", default=None)
    g.add_argument("--use_post_ln", action="store_true", default=None)
    g.add_argument("--glu_activation", type=str, default=None)
    g.add_argument("--position_embedding_type", type=str, default=None)
    g.add_argument("--rope_scaling_factor", type=float, default=None)
    g.add_argument("--rope_theta", type=float, default=None)
    g.add_argument("--parallel_attn", action="store_true", default=None)
    g.add_argument("--parallel_layernorm", action="store_true", default=None)
    g.add_argument("--no_tie_embed_logits", action="store_true")

    g = p.add_argument_group("regularization")  # ref :544-574
    g.add_argument("--hidden_dropout", type=float, default=None)
    g.add_argument("--attention_dropout", type=float, default=None)
    g.add_argument("--lima_dropout", action="store_true", default=None)
    g.add_argument("--weight_decay", type=float, default=0.01)
    g.add_argument("--start_weight_decay", type=float, default=None)
    g.add_argument("--end_weight_decay", type=float, default=None)
    g.add_argument("--weight_decay_incr_style", default="constant")
    g.add_argument("--clip_grad", type=float, default=1.0)
    g.add_argument("--adam_beta1", type=float, default=0.9)
    g.add_argument("--adam_beta2", type=float, default=0.999)
    g.add_argument("--adam_eps", type=float, default=1e-8)
    g.add_argument("--sgd_momentum", type=float, default=0.9)

    g = p.add_argument_group("training")  # ref :579-691
    g.add_argument("--micro_batch_size", type=int, default=1)
    g.add_argument("--global_batch_size", type=int, default=None)
    g.add_argument("--rampup_batch_size", nargs=3, type=int, default=None)
    g.add_argument("--train_iters", type=int, default=None)
    g.add_argument("--exit_interval", type=int, default=None)
    g.add_argument("--exit_duration_in_mins", type=float, default=None)
    g.add_argument("--exit_signal_handler", action="store_true")
    # sentinel-file autoresume (TPU analogue of ref --adlr_autoresume)
    g.add_argument("--autoresume_file", type=str, default=None)
    g.add_argument("--autoresume_interval", type=int, default=50)
    g.add_argument("--optimizer", default="adam", choices=["adam", "sgd"])
    g.add_argument("--dataloader_type", default="single",
                   choices=["single", "cyclic"])
    g.add_argument("--use_flash_attn", action="store_true", default=None)
    # Llama presets default flash ON; this is the CLI off-switch.
    g.add_argument("--no_use_flash_attn", dest="use_flash_attn",
                   action="store_false")
    g.add_argument("--recompute_granularity", default=None,
                   choices=[None, "full", "selective"])
    g.add_argument("--sequence_parallel", action="store_true")

    g = p.add_argument_group("learning rate")  # ref :710-747
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--lr_decay_style", default="linear",
                   choices=["constant", "linear", "cosine", "inverse-square-root"])
    g.add_argument("--lr_decay_iters", type=int, default=None)
    g.add_argument("--lr_warmup_fraction", type=float, default=None)
    g.add_argument("--lr_warmup_iters", type=int, default=0)
    g.add_argument("--min_lr", type=float, default=0.0)
    g.add_argument("--use_checkpoint_opt_param_scheduler", action="store_true")
    g.add_argument("--override_opt_param_scheduler", action="store_true")

    g = p.add_argument_group("checkpointing")  # ref :751-779
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save_interval", type=int, default=None)
    g.add_argument("--load", type=str, default=None)
    # ref: --use_checkpoint_args (checkpointing.py:476 load_args_from_
    # checkpoint): take the model architecture from the checkpoint's meta
    g.add_argument("--use_checkpoint_args", action="store_true")
    g.add_argument("--finetune", action="store_true")
    g.add_argument("--no_load_optim", action="store_true")
    g.add_argument("--no_load_rng", action="store_true")

    g = p.add_argument_group("mixed precision")  # ref :783-815
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss_scale", type=float, default=None)
    g.add_argument("--initial_loss_scale", type=float, default=2.0**32)
    g.add_argument("--loss_scale_window", type=int, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)

    g = p.add_argument_group("distributed")  # ref :820-866
    g.add_argument("--tensor_model_parallel_size", type=int, default=1)
    g.add_argument("--pipeline_model_parallel_size", type=int, default=1)
    # --num_layers_per_virtual_pipeline_stage (ref arguments.py:828) is
    # deliberately unsupported: the per-tick-remat schedule makes
    # num_microbatches the bubble lever (see ParallelConfig note); accept
    # and reject it explicitly so reference scripts fail loudly.
    g.add_argument("--num_layers_per_virtual_pipeline_stage", type=int,
                   default=None, help=argparse.SUPPRESS)
    g.add_argument("--use_distributed_optimizer", action="store_true")
    g.add_argument("--data_parallel_size", type=int, default=None)
    # context parallelism (ring attention over the sequence axis) — a
    # beyond-reference long-context axis; see ParallelConfig.
    g.add_argument("--context_parallel_size", type=int, default=1)

    g = p.add_argument_group("validation")  # ref :870-877
    g.add_argument("--eval_iters", type=int, default=100)
    g.add_argument("--eval_interval", type=int, default=1000)

    g = p.add_argument_group("data")  # ref :881-962
    g.add_argument("--data_path", nargs="*", default=None)
    g.add_argument("--split", default="969,30,1")
    g.add_argument("--seq_length", type=int, default=2048)
    g.add_argument("--tokenizer_type", type=str, default=None)
    g.add_argument("--vocab_file", type=str, default=None)
    g.add_argument("--merges_file", type=str, default=None)
    g.add_argument("--tokenizer_model", type=str, default=None)
    g.add_argument("--null_vocab_size", type=int, default=None)
    g.add_argument("--reset_position_ids", action="store_true")
    g.add_argument("--reset_attention_mask", action="store_true")
    g.add_argument("--eod_mask_loss", action="store_true")
    g.add_argument("--seed", type=int, default=1234)

    g = p.add_argument_group("logging")  # ref :477-541
    g.add_argument("--log_interval", type=int, default=100)
    g.add_argument("--tensorboard_dir", type=str, default=None)
    g.add_argument("--wandb_logger", action="store_true")
    g.add_argument("--log_params_norm", action="store_true")
    g.add_argument("--log_num_zeros_in_grad", action="store_true")
    g.add_argument("--profile", action="store_true")
    g.add_argument("--profile_step_start", type=int, default=10)
    g.add_argument("--profile_step_end", type=int, default=12)
    g.add_argument("--profile_dir", type=str, default=None)

    return p


def args_to_configs(args, padded_vocab_size: int):
    """Map the parsed namespace onto typed configs (the reference's
    validate_args derivations, arguments.py:52-345)."""
    tp = args.tensor_model_parallel_size
    pp = args.pipeline_model_parallel_size
    if getattr(args, "num_layers_per_virtual_pipeline_stage", None):
        raise SystemExit(
            "--num_layers_per_virtual_pipeline_stage is unsupported by "
            "design: the per-tick-remat pipeline schedule makes "
            "num_microbatches the bubble lever (see ParallelConfig)."
        )

    overrides = {}
    for name in (
        "num_layers", "hidden_size", "ffn_hidden_size", "num_attention_heads",
        "num_attention_heads_kv", "kv_channels", "layernorm_epsilon",
        "glu_activation", "position_embedding_type", "rope_scaling_factor",
        "rope_theta", "hidden_dropout", "attention_dropout", "lima_dropout",
        "use_flash_attn", "recompute_granularity", "use_bias", "use_rms_norm",
        "use_post_ln", "parallel_attn", "parallel_layernorm",
    ):
        v = getattr(args, name)
        if v is not None:
            overrides[name] = v
    if args.max_position_embeddings is not None:
        overrides["max_position_embeddings"] = args.max_position_embeddings
    else:
        overrides["max_position_embeddings"] = args.seq_length
    overrides["make_vocab_size_divisible_by"] = args.make_vocab_size_divisible_by
    if args.no_tie_embed_logits:
        overrides["tie_embed_logits"] = False
    if args.fp16:
        overrides["params_dtype"] = jnp.float32
        overrides["compute_dtype"] = jnp.float16

    name = args.model_name
    if name in ("llama", "llama2"):
        mcfg = llama_config(args.model_size, version=1 if name == "llama" else 2,
                            seq_length=args.seq_length, tp=tp, **overrides)
    elif name == "codellama":
        from megatron_llm_tpu.config import codellama_config

        mcfg = codellama_config(args.model_size, seq_length=args.seq_length,
                                **overrides)
    elif name == "falcon":
        mcfg = falcon_config(args.model_size, seq_length=args.seq_length,
                             tp=tp, **overrides)
    elif name in ("bert", "t5"):
        from megatron_llm_tpu.config import bert_config, t5_config

        preset = bert_config if name == "bert" else t5_config
        mcfg = preset(
            num_layers=overrides.pop("num_layers", 12),
            hidden_size=overrides.pop("hidden_size", 768),
            num_attention_heads=overrides.pop("num_attention_heads", 12),
            seq_length=args.seq_length,
            tp=tp,
            **overrides,
        )
    else:
        mcfg = gpt_config(
            num_layers=overrides.pop("num_layers", 12),
            hidden_size=overrides.pop("hidden_size", 768),
            num_attention_heads=overrides.pop("num_attention_heads", 12),
            seq_length=args.seq_length,
            tp=tp,
            **overrides,
        )
    import dataclasses as _dc

    mcfg = _dc.replace(mcfg, padded_vocab_size=mcfg.pad_vocab_size(
        padded_vocab_size, tp) if padded_vocab_size else mcfg.padded_vocab_size)

    import jax

    cp = getattr(args, "context_parallel_size", 1) or 1
    dp = args.data_parallel_size
    if dp is None:
        dp = max(1, len(jax.devices()) // (tp * pp * cp))
    gbs = args.global_batch_size or args.micro_batch_size * dp
    num_micro = gbs // (args.micro_batch_size * dp)
    pcfg = ParallelConfig(
        data_parallel_size=dp,
        pipeline_parallel_size=pp,
        tensor_parallel_size=tp,
        context_parallel_size=cp,
        sequence_parallel=args.sequence_parallel,
        use_distributed_optimizer=args.use_distributed_optimizer,
        num_microbatches=num_micro,
    )

    tcfg = TrainConfig(
        micro_batch_size=args.micro_batch_size,
        global_batch_size=gbs,
        rampup_batch_size=tuple(args.rampup_batch_size)
        if args.rampup_batch_size else None,
        train_iters=args.train_iters,
        exit_interval=args.exit_interval,
        exit_duration_in_mins=args.exit_duration_in_mins,
        exit_signal_handler=args.exit_signal_handler,
        autoresume_file=args.autoresume_file,
        autoresume_interval=args.autoresume_interval,
        optimizer=args.optimizer,
        lr=args.lr,
        min_lr=args.min_lr,
        lr_decay_style=args.lr_decay_style,
        lr_decay_iters=args.lr_decay_iters,
        lr_warmup_iters=args.lr_warmup_iters,
        lr_warmup_fraction=args.lr_warmup_fraction,
        use_checkpoint_opt_param_scheduler=args.use_checkpoint_opt_param_scheduler,
        override_opt_param_scheduler=args.override_opt_param_scheduler,
        weight_decay=args.weight_decay,
        start_weight_decay=args.start_weight_decay,
        end_weight_decay=args.end_weight_decay,
        weight_decay_incr_style=args.weight_decay_incr_style,
        clip_grad=args.clip_grad,
        adam_beta1=args.adam_beta1,
        adam_beta2=args.adam_beta2,
        adam_eps=args.adam_eps,
        sgd_momentum=args.sgd_momentum,
        fp16=args.fp16,
        bf16=not args.fp16,
        loss_scale=args.loss_scale,
        initial_loss_scale=args.initial_loss_scale,
        loss_scale_window=args.loss_scale_window,
        hysteresis=args.hysteresis,
        save=args.save,
        load=args.load,
        save_interval=args.save_interval,
        finetune=args.finetune,
        no_load_optim=args.no_load_optim,
        no_load_rng=args.no_load_rng,
        log_interval=args.log_interval,
        eval_interval=args.eval_interval,
        eval_iters=args.eval_iters,
        tensorboard_dir=args.tensorboard_dir,
        wandb_logger=args.wandb_logger,
        log_params_norm=args.log_params_norm,
        log_num_zeros_in_grad=args.log_num_zeros_in_grad,
        profile=args.profile,
        profile_step_start=args.profile_step_start,
        profile_step_end=args.profile_step_end,
        profile_dir=args.profile_dir,
        seed=args.seed,
    )

    dargs = DataArgs(
        data_path=args.data_path,
        split=args.split,
        tokenizer_type=args.tokenizer_type,
        vocab_file=args.vocab_file,
        merges_file=args.merges_file,
        tokenizer_model=args.tokenizer_model,
        seq_length=args.seq_length,
        reset_position_ids=args.reset_position_ids,
        reset_attention_mask=args.reset_attention_mask,
        eod_mask_loss=args.eod_mask_loss,
        null_vocab_size=args.null_vocab_size,
        dataloader_type=args.dataloader_type,
    )
    return mcfg, pcfg, tcfg, dargs
