"""Loss watchdog: spike/NaN detection, skip accounting, rollback policy.

Production LLM runs hit loss spikes — bad data shards, optimizer-state
blowups after restarts, silent hardware corruption (PAPERS.md: the
Llama 2 and Megatron-LM training reports both describe operator-driven
restart-and-skip around spikes). This module makes that loop automatic:

- the watchdog keeps a ROBUST running statistic of recent good losses
  (median + MAD over a sliding window — a spike must not poison the very
  estimate that is supposed to catch it, which rules out plain
  mean/variance);
- a step is BAD when its loss is non-finite or exceeds
  median + k_sigma * (1.4826 * MAD). The trainer feeds the same
  threshold into the jitted train step as a traced scalar, where it
  rides the fp16 scaler's skip machinery (`optimizer_step(found_inf=)`)
  — so a bad step leaves params/optimizer untouched on device for bf16
  runs exactly like an fp16 overflow does, with no extra host round
  trip;
- `spike_rollback_patience` consecutive bad steps escalate to a
  ROLLBACK: the trainer reloads the last complete checkpoint and keeps
  the data iterator where it is, fast-forwarding past the poison window
  (training/trainer.py `_rollback`).

Counters (`skipped`, `rollbacks`) are exported through the timers-gauge
path and WandB (`loss_watchdog_skipped` / `loss_watchdog_rollbacks`).
"""

from __future__ import annotations

import math

from megatron_llm_tpu.telemetry.sentinel import RobustWindow


class LossWatchdog:
    """Host-side spike detector with skip/rollback bookkeeping.

    `k_sigma <= 0` disables SPIKE detection (non-finite losses are still
    bad — a NaN loss must never enter the window or the weights).
    `patience <= 0` disables rollback escalation (skip-only mode)."""

    def __init__(self, k_sigma: float = 0.0, window: int = 64,
                 patience: int = 0, min_history: int = 8,
                 recorder=None):
        assert window >= 4 and min_history >= 2
        self.k_sigma = k_sigma
        self.patience = patience
        # optional telemetry.FlightRecorder (ISSUE 13): every BAD
        # verdict and every rollback lands in the flight ring keyed by
        # step, so a dumped artifact shows the verdict trail that led
        # to the death/rollback — not just the final counter values
        self.recorder = recorder
        # the ONE robust statistic, shared with the perf-regression
        # sentinel (telemetry/sentinel.py, ISSUE 15): median + MAD over
        # a sliding window with the min_history arming clamp
        self._stat = RobustWindow(window=window, min_history=min_history)
        self.min_history = self._stat.min_history
        self.consecutive_bad = 0
        self.skipped = 0
        self.rollbacks = 0

    # -- robust running stat ----------------------------------------------

    def _median_mad(self):
        return self._stat.median_mad()

    def threshold(self) -> float:
        """Loss value above which the current step is a spike; +inf while
        spike detection is off or the window is too short to be trusted
        (RobustWindow.threshold — 1.4826*MAD sigma with the flat-window
        floor)."""
        return self._stat.threshold(self.k_sigma)

    # -- per-step protocol -------------------------------------------------

    def observe(self, loss: float, step: int = -1) -> bool:
        """Feed one step's loss; returns True when the step was BAD
        (non-finite or spiking) — the trainer's in-step threshold already
        skipped the update for exactly these steps, so the watchdog and
        the device agree by construction (same threshold value).
        `step` is the correlation key the flight-record verdict events
        carry (the trainer passes its iteration)."""
        thr = self.threshold()
        bad = (not math.isfinite(loss)) or loss > thr
        if bad:
            self.consecutive_bad += 1
            self.skipped += 1
            if self.recorder is not None:
                self.recorder.record(
                    "watchdog_bad", step=step, loss=loss,
                    threshold=thr, streak=self.consecutive_bad)
        else:
            self.consecutive_bad = 0
            self._stat.push(loss)
        return bad

    def should_rollback(self) -> bool:
        return self.patience > 0 and self.consecutive_bad >= self.patience

    def note_rollback(self, step: int = -1,
                      restored_step: int = -1) -> None:
        """Reset after the trainer reloaded a checkpoint: the window is
        cleared (it described the diverged trajectory, not the restored
        one) and the bad-streak ends."""
        self.rollbacks += 1
        self.consecutive_bad = 0
        self._stat.clear()
        if self.recorder is not None:
            self.recorder.record("watchdog_rollback", step=step,
                                 restored_step=restored_step,
                                 rollback=self.rollbacks)

    def counters(self) -> dict:
        return {"loss_watchdog_skipped": self.skipped,
                "loss_watchdog_rollbacks": self.rollbacks}
