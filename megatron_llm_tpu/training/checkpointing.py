"""Checkpoint save/load — orbax/tensorstore, mesh-shape independent.

Parity target: ref megatron/checkpointing.py — iteration-numbered
directories, a `latest_checkpointed_iteration.txt` tracker (:170),
`--finetune` semantics (reset iteration, skip optim/rng, :583-625),
arg cross-checking (:35-66), rng state for bitwise resume (:217-240).

TPU-first differences: one orbax checkpoint holds the whole (sharded)
params/optimizer tree keyed by logical names — tensorstore reshards on load
under any mesh shape, which deletes the entire reason the reference needs
tools/checkpoint_util.py's tp/pp re-partitioner (SURVEY.md §5). Layout:

    <save>/iter_0000100/{model,optim,meta.json,COMPLETE}
    <save>/latest_checkpointed_iteration.txt

Fault tolerance (ISSUE 5):
- the tracker is written ATOMICALLY (tmp in the same directory + fsync +
  os.rename) — a crash mid-write can never corrupt it;
- every checkpoint directory carries a `COMPLETE` sentinel, written LAST
  (after the orbax commits and meta.json land), so a torn save is
  distinguishable from a finished one without trusting mtimes;
- `load_checkpoint` scans BACKWARD past incomplete/corrupt iteration
  directories to the newest complete one — a preempted pod resumes from
  the last good save with a loud warning, never a stack trace;
- `CheckpointManager` is the ASYNC save path: `save()` returns to the
  train loop right after the device→host copy (orbax async), a single
  save is in flight at a time (a new save waits on the previous), the
  sentinel/tracker/retention-GC finalization runs on a background
  thread, and `wait_until_finished()` is only required at exit. The
  blocking portion of each save is surfaced as the `ckpt_blocked_ms`
  timers gauge.
- `--keep_latest_n` retention GC deletes old iteration directories but
  never the one currently being written or the one a resume read.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Iterable, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

TRACKER_FILENAME = "latest_checkpointed_iteration.txt"
COMPLETE_FILENAME = "COMPLETE"
_ITER_DIR_RE = re.compile(r"^iter_(\d{7})$")


def checkpoint_dir(save_dir: str, iteration: int, release: bool = False) -> str:
    """ref: get_checkpoint_name (checkpointing.py:77-96) directory level."""
    name = "release" if release else f"iter_{iteration:07d}"
    return os.path.join(save_dir, name)


def read_tracker(load_dir: str) -> Tuple[Optional[int], bool]:
    """ref: read_metadata (checkpointing.py:160-216)."""
    path = os.path.join(load_dir, TRACKER_FILENAME)
    if not os.path.isfile(path):
        return None, False
    with open(path) as f:
        raw = f.read().strip()
    if raw == "release":
        return None, True
    return int(raw), False


def _atomic_write(path: str, data: str) -> None:
    """tmp in the SAME directory + fsync + rename: the write is all-or-
    nothing on every POSIX filesystem (rename within a directory is
    atomic; the fsync orders the data before the name swap)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


def _write_tracker(save_dir: str, iteration: int, release: bool = False) -> None:
    """Crash-safe tracker update: a SIGKILL between any two instructions
    leaves either the old tracker or the new one, never a torn file."""
    _atomic_write(os.path.join(save_dir, TRACKER_FILENAME),
                  "release" if release else str(iteration))


def _mark_complete(path: str) -> None:
    """The per-checkpoint COMPLETE sentinel — written LAST, so its
    presence certifies every artifact (model/optim/meta.json) landed."""
    _atomic_write(os.path.join(path, COMPLETE_FILENAME), "1")


def is_checkpoint_complete(path: str) -> bool:
    return os.path.isfile(os.path.join(path, COMPLETE_FILENAME))


def list_iteration_checkpoints(load_dir: str) -> List[Tuple[int, str]]:
    """(iteration, path) for every iter_* directory, newest first."""
    out = []
    try:
        names = os.listdir(load_dir)
    except OSError:
        return []
    for name in names:
        m = _ITER_DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(load_dir, name)):
            out.append((int(m.group(1)), os.path.join(load_dir, name)))
    out.sort(reverse=True)
    return out


def gc_checkpoints(save_dir: str, keep_latest_n: int,
                   protect: Iterable[str] = ()) -> List[str]:
    """Retention GC: keep the newest `keep_latest_n` COMPLETE iteration
    checkpoints, delete every older iter_* directory — including torn
    debris below the retention horizon. Never touches `release`, the
    tracker, or any path in `protect` (the checkpoint currently being
    written and the one a resume read from). Returns the deleted paths."""
    if keep_latest_n is None or keep_latest_n < 1:
        return []
    protect = {os.path.abspath(p) for p in protect}
    complete = [(it, p) for it, p in list_iteration_checkpoints(save_dir)
                if is_checkpoint_complete(p)]
    keep = {os.path.abspath(p) for _, p in complete[:keep_latest_n]}
    if complete:
        horizon = complete[min(keep_latest_n, len(complete)) - 1][0]
    else:
        return []  # nothing certified complete yet: delete nothing
    deleted = []
    for it, p in list_iteration_checkpoints(save_dir):
        ap = os.path.abspath(p)
        if ap in keep or ap in protect:
            continue
        if it >= horizon:
            # newer-than-horizon incomplete dirs may be an in-flight
            # async save on another manager: leave them alone
            continue
        try:
            shutil.rmtree(p)
            deleted.append(p)
        except OSError as e:
            print(f"WARNING: checkpoint GC could not delete {p}: {e}",
                  flush=True)
    return deleted


def _config_meta(model_cfg) -> dict:
    d = dataclasses.asdict(model_cfg)
    return {k: (str(v) if not isinstance(v, (int, float, bool, str, type(None), list, tuple)) else v)
            for k, v in d.items()}


class CheckpointArchMismatch(ValueError):
    """Raised on checkpoint-vs-config architecture mismatch. A distinct
    type so load_checkpoint's torn-save backward scan can re-raise it
    (user error) while falling back on arbitrary restore failures —
    tensorstore raises plain ValueError for corrupt data too."""


def check_checkpoint_args(saved: dict, model_cfg) -> None:
    """ref: check_checkpoint_args (checkpointing.py:35-66) — error on
    architecture mismatch."""
    current = _config_meta(model_cfg)
    critical = (
        "num_layers", "hidden_size", "num_attention_heads",
        "num_attention_heads_kv", "ffn_hidden_size", "padded_vocab_size",
        "position_embedding_type", "glu_activation", "use_rms_norm",
        "use_bias", "tie_embed_logits", "parallel_attn", "parallel_layernorm",
    )
    for k in critical:
        if k in saved and saved[k] != current[k]:
            raise CheckpointArchMismatch(
                f"checkpoint/config mismatch for {k}: "
                f"checkpoint has {saved[k]!r}, config has {current[k]!r}"
            )


def _build_meta(iteration, model_cfg, scheduler_state,
                consumed_train_samples, rng_key, extra_meta) -> dict:
    meta = {
        "iteration": iteration,
        "consumed_train_samples": consumed_train_samples,
        "scheduler": scheduler_state or {},
        "config": _config_meta(model_cfg) if model_cfg is not None else {},
        "rng_key": np.asarray(jax.random.key_data(rng_key)).tolist()
        if rng_key is not None else None,
        "checkpoint_version": 3.0,
    }
    meta.update(extra_meta or {})
    return meta


def _opt_state_tree(opt_state) -> dict:
    return {"step": opt_state.step, "m": opt_state.m,
            **({"v": opt_state.v} if opt_state.v is not None else {}),
            **({"scaler": opt_state.scaler}
               if getattr(opt_state, "scaler", None) else {})}


def save_checkpoint(
    save_dir: str,
    iteration: int,
    params: Any,
    opt_state: Any = None,
    model_cfg=None,
    scheduler_state: Optional[dict] = None,
    consumed_train_samples: int = 0,
    rng_key: Optional[jax.Array] = None,
    extra_meta: Optional[dict] = None,
    release: bool = False,
) -> str:
    """Synchronous save (ref: save_checkpoint checkpointing.py:243-338;
    `release=True` writes the converter layout, ref "release" naming
    :93). Blocks until committed; the train loop uses CheckpointManager
    instead so the step time only pays the device→host copy. Both paths
    share the crash-safe layout: COMPLETE sentinel last, atomic
    tracker."""
    save_dir = os.path.abspath(save_dir)  # orbax requires absolute paths
    path = checkpoint_dir(save_dir, iteration, release=release)
    os.makedirs(save_dir, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "model"), params, force=True)
    if opt_state is not None:
        ckptr.save(os.path.join(path, "optim"), _opt_state_tree(opt_state),
                   force=True)
    meta = _build_meta(iteration, model_cfg, scheduler_state,
                       consumed_train_samples, rng_key, extra_meta)
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    ckptr.wait_until_finished()
    _mark_complete(path)
    _write_tracker(save_dir, iteration, release=release)
    return path


class CheckpointManager:
    """Async crash-safe checkpoint writer for ONE save directory.

    `save()` hands the on-device arrays to orbax's async path (two
    AsyncCheckpointers so the model and optimizer device→host copies
    overlap instead of serializing behind each other's commit) and
    returns to the train loop immediately; a background finalizer thread
    waits for the tensorstore commits, then writes meta.json, the
    COMPLETE sentinel (last), the atomic tracker, and runs retention GC.
    Exactly ONE save is in flight: a new `save()` first waits on the
    previous finalizer, so checkpoints can never interleave and the
    tracker only ever advances over certified-complete directories.

    `last_blocked_ms` is the wall time the caller was actually stalled
    by the most recent `save()` (previous-save wait + device→host copy)
    — exported as the `ckpt_blocked_ms` timers gauge by the trainer and
    measured against the synchronous save wall time by bench.py's
    `extra.ckpt` row. Call `wait_until_finished()` (or `close()`) before
    process exit so the final save commits."""

    def __init__(self, save_dir: str, keep_latest_n: Optional[int] = None,
                 async_save: bool = True, recorder=None):
        self.save_dir = os.path.abspath(save_dir)
        self.keep_latest_n = keep_latest_n
        self.async_save = async_save
        # optional telemetry.FlightRecorder (ISSUE 13): the save
        # lifecycle (dispatch + blocked ms, background certification)
        # lands in the flight ring keyed by iteration — a postmortem
        # shows whether the run died inside/behind a save
        self.recorder = recorder
        self._model_ckptr = ocp.StandardCheckpointer()
        self._optim_ckptr = ocp.StandardCheckpointer()
        self._finalizer: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._inflight_path: Optional[str] = None
        # the checkpoint a resume read from — GC must never delete it
        self._protected: set = set()
        self.last_blocked_ms: float = 0.0
        self.total_blocked_ms: float = 0.0
        self.saves: int = 0

    def protect(self, path: Optional[str]) -> None:
        if path:
            self._protected.add(os.path.abspath(path))

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                f"previous async checkpoint save failed: {err!r}") from err

    def wait_until_finished(self) -> None:
        """Block until the in-flight save (if any) is fully committed —
        the ONLY place the train loop ever pays the full write latency,
        and it only calls it at exit/rollback. Re-raises a background
        save failure loudly."""
        if self._finalizer is not None:
            self._finalizer.join()
            self._finalizer = None
        self._inflight_path = None
        self._raise_pending()

    close = wait_until_finished

    def _finalize(self, path: str, iteration: int, meta: dict) -> None:
        try:
            self._model_ckptr.wait_until_finished()
            self._optim_ckptr.wait_until_finished()
            if jax.process_index() == 0:
                with open(os.path.join(path, "meta.json"), "w") as f:
                    json.dump(meta, f, indent=1)
                _mark_complete(path)  # LAST artifact: certifies the save
                _write_tracker(self.save_dir, iteration)
                if self.keep_latest_n:
                    gc_checkpoints(
                        self.save_dir, self.keep_latest_n,
                        protect=self._protected | {path})
            if self.recorder is not None:
                self.recorder.record("ckpt_certified", step=iteration)
        except BaseException as e:  # surfaced on the next save()/wait()
            if self.recorder is not None:
                self.recorder.record("ckpt_failed", step=iteration,
                                     error=repr(e))
            self._error = e

    def save(
        self,
        iteration: int,
        params: Any,
        opt_state: Any = None,
        model_cfg=None,
        scheduler_state: Optional[dict] = None,
        consumed_train_samples: int = 0,
        rng_key: Optional[jax.Array] = None,
        extra_meta: Optional[dict] = None,
    ) -> str:
        t0 = time.perf_counter()
        # single in-flight: the previous save must be certified before a
        # newer one may start (tracker ordering + bounded host memory)
        self.wait_until_finished()
        path = checkpoint_dir(self.save_dir, iteration)
        os.makedirs(self.save_dir, exist_ok=True)
        if not self.async_save:
            out = save_checkpoint(
                self.save_dir, iteration, params, opt_state, model_cfg,
                scheduler_state, consumed_train_samples, rng_key,
                extra_meta)
            # retention holds in BOTH modes — sync saves certify
            # inline, so GC runs inline too
            if self.keep_latest_n and jax.process_index() == 0:
                gc_checkpoints(self.save_dir, self.keep_latest_n,
                               protect=self._protected | {path})
            self.last_blocked_ms = (time.perf_counter() - t0) * 1e3
            self.total_blocked_ms += self.last_blocked_ms
            self.saves += 1
            if self.recorder is not None:
                self.recorder.record(
                    "ckpt_certified", step=iteration,
                    blocked_ms=round(self.last_blocked_ms, 3))
            return out
        # async: these return after the device→host copy; tensorstore
        # writes + the directory rename happen on orbax's threads
        self._model_ckptr.save(os.path.join(path, "model"), params,
                               force=True)
        if opt_state is not None:
            self._optim_ckptr.save(os.path.join(path, "optim"),
                                   _opt_state_tree(opt_state), force=True)
        meta = _build_meta(iteration, model_cfg, scheduler_state,
                           consumed_train_samples, rng_key, extra_meta)
        self._inflight_path = path
        self._finalizer = threading.Thread(
            target=self._finalize, args=(path, iteration, meta),
            name=f"ckpt-finalize-{iteration}", daemon=False)
        self._finalizer.start()
        self.last_blocked_ms = (time.perf_counter() - t0) * 1e3
        self.total_blocked_ms += self.last_blocked_ms
        self.saves += 1
        if self.recorder is not None:
            self.recorder.record(
                "ckpt_dispatched", step=iteration,
                blocked_ms=round(self.last_blocked_ms, 3))
        return path


# The ARCHITECTURE fields --use_checkpoint_args may overlay — exactly the
# check_checkpoint_args critical set plus the shape-determining extras.
# Training knobs (dropout, recompute, flash, seq_length, ...) stay with
# the CLI, matching the reference's _set_arg force-list
# (ref: load_args_from_checkpoint checkpointing.py:506-560).
_CHECKPOINT_ARCH_FIELDS = (
    "num_layers", "hidden_size", "num_attention_heads",
    "num_attention_heads_kv", "kv_channels", "ffn_hidden_size",
    "padded_vocab_size", "position_embedding_type", "glu_activation",
    "hidden_act", "use_rms_norm", "use_bias", "tie_embed_logits",
    "parallel_attn", "parallel_layernorm", "use_post_ln",
    "layernorm_epsilon", "rope_theta", "rope_scaling_factor",
    "max_position_embeddings", "num_tokentypes", "add_binary_head",
)


def load_model_config_from_checkpoint(load_dir: str, mcfg):
    """Overlay the architecture recorded in a checkpoint's meta.json onto
    `mcfg` (ref: load_args_from_checkpoint checkpointing.py:476-560 +
    --use_checkpoint_args). Only the architecture fields listed above are
    taken (training knobs keep their CLI values); None round-trips.
    Returns the updated config, or the input unchanged when no
    checkpoint/meta exists."""
    iteration, release = read_tracker(load_dir)
    if iteration is None and not release:
        return mcfg
    meta_path = os.path.join(
        checkpoint_dir(load_dir, iteration or 0, release=release),
        "meta.json",
    )
    if not os.path.exists(meta_path):
        return mcfg
    with open(meta_path) as f:
        saved = json.load(f).get("config", {})
    updates = {}
    for name in _CHECKPOINT_ARCH_FIELDS:
        if name not in saved or not hasattr(mcfg, name):
            continue
        val = saved[name]
        cur = getattr(mcfg, name)
        if not isinstance(val, (int, float, bool, str, type(None))):
            continue
        if val is None or cur is None:
            if val != cur:
                updates[name] = val
        elif val != cur:
            updates[name] = type(cur)(val)
    if updates:
        print(f" > using checkpoint args from {meta_path}: "
              f"{sorted(updates)}", flush=True)
        mcfg = dataclasses.replace(mcfg, **updates)
    return mcfg


def _load_candidates(load_dir: str):
    """Resume candidates (newest first) plus the `intended` resume
    iteration. Ordering is strictly by iteration, NOT tracker-first: a
    crash between the COMPLETE sentinel and the tracker write leaves the
    tracker one save stale, and preferring it would silently discard a
    fully certified newer checkpoint. Directories without the COMPLETE
    sentinel are skipped (torn saves) — unless NO directory in load_dir
    has one (a pre-sentinel legacy layout), in which case everything is
    attempted and corruption is caught at restore time instead.
    `intended` — what a fully healthy directory would have resumed (the
    newer of tracker target and newest directory) — drives the caller's
    resumed-from-older warning."""
    tracker_iter, release = read_tracker(load_dir)
    iters = list_iteration_checkpoints(load_dir)
    any_sentinel = any(is_checkpoint_complete(p) for _, p in iters)
    out: List[Tuple[Optional[int], str, bool]] = []
    if release:
        out.append((None, checkpoint_dir(load_dir, 0, release=True), True))
    for it, path in iters:
        if any_sentinel and not is_checkpoint_complete(path):
            print(f"WARNING: skipping incomplete checkpoint {path} "
                  f"(no {COMPLETE_FILENAME} sentinel — torn save)",
                  flush=True)
            continue
        out.append((it, path, False))
    newest = iters[0][0] if iters else None
    intended = max((x for x in (tracker_iter, newest) if x is not None),
                   default=None)
    return out, intended


def _abstract_leaf(x):
    """Template leaf -> restore target. Sharding-less abstract leaves
    (jax.eval_shape output) get an explicit default-device sharding —
    this orbax line's to_shape_dtype_struct chokes on sharding=None, and
    letting orbax read the sharding file instead would resurrect the
    SAVED topology, which is exactly wrong for cross-mesh restore."""
    if (isinstance(x, jax.ShapeDtypeStruct)
            and getattr(x, "sharding", None) is None):
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype,
            sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]))
    return ocp.utils.to_shape_dtype_struct(x)


def _restore_one(path: str, release: bool, params_template,
                 opt_state_template, model_cfg, finetune: bool,
                 no_load_optim: bool, no_load_rng: bool):
    """Restore a single checkpoint directory; raises on torn/corrupt
    artifacts (the caller's backward scan catches and falls back).
    Architecture mismatches raise CheckpointArchMismatch PAST the scan —
    a wrong --num_layers is a user error, not a torn save."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if model_cfg is not None and meta.get("config"):
        check_checkpoint_args(meta["config"], model_cfg)

    ckptr = ocp.StandardCheckpointer()
    abstract_params = jax.tree.map(_abstract_leaf, params_template)
    params = ckptr.restore(os.path.join(path, "model"), abstract_params)

    # release checkpoints (converter output) carry weights only: load like
    # --finetune — no optimizer/rng, iteration 0 (ref: checkpointing.py:
    # 583-625, release naming :93)
    opt_state = None
    if (opt_state_template is not None and not finetune and not no_load_optim
            and not release):
        from megatron_llm_tpu.optimizer.optimizer import OptimizerState

        tmpl = {"step": opt_state_template.step, "m": opt_state_template.m}
        if opt_state_template.v is not None:
            tmpl["v"] = opt_state_template.v
        if getattr(opt_state_template, "scaler", None):
            tmpl["scaler"] = opt_state_template.scaler
        abstract_opt = jax.tree.map(_abstract_leaf, tmpl)
        restored = ckptr.restore(os.path.join(path, "optim"), abstract_opt)
        opt_state = OptimizerState(
            step=restored["step"], m=restored["m"], v=restored.get("v"),
            scaler=restored.get("scaler"),
        )

    # --finetune resets iteration and skips optim/rng (ref :583-625)
    out_iteration = 0 if (finetune or release) else meta["iteration"]
    if finetune or no_load_rng or release:
        meta = dict(meta)
        meta["rng_key"] = None
    return params, opt_state, meta, out_iteration


def load_checkpoint(
    load_dir: str,
    params_template: Any,
    opt_state_template: Any = None,
    model_cfg=None,
    finetune: bool = False,
    no_load_optim: bool = False,
    no_load_rng: bool = False,
    iteration: Optional[int] = None,
):
    """ref: load_checkpoint (checkpointing.py:561-730).

    Templates are abstract (jax.eval_shape / ShapeDtypeStruct with sharding)
    or concrete trees; orbax restores into the template's shardings, so the
    same checkpoint loads under any mesh. Returns
    (params, opt_state|None, meta, iteration), plus `loaded_path` on the
    meta dict (retention GC protects it).

    Fault tolerance: when the tracker (or newest directory) names a torn
    or corrupt save — missing meta.json, partial orbax arrays, missing
    COMPLETE sentinel — the scan falls BACK through older complete
    checkpoints with a loud warning per skip. A preempted pod therefore
    always resumes from the newest certified checkpoint; it never
    crashes on the one the preemption tore. An explicitly requested
    `iteration` is exempt from the scan (you asked for that one: a
    problem with it is an error)."""
    load_dir = os.path.abspath(load_dir)  # orbax requires absolute paths

    if iteration is not None:
        path = checkpoint_dir(load_dir, iteration)
        out = _restore_one(path, False, params_template,
                           opt_state_template, model_cfg, finetune,
                           no_load_optim, no_load_rng)
        out[2]["loaded_path"] = path
        return out

    candidates, intended = _load_candidates(load_dir)
    if not candidates:
        return None  # no checkpoint (ref returns 0 + warns)

    for it, path, release in candidates:
        try:
            out = _restore_one(path, release, params_template,
                               opt_state_template, model_cfg, finetune,
                               no_load_optim, no_load_rng)
        except CheckpointArchMismatch:
            raise  # user error, not a torn save
        except Exception as e:  # noqa: BLE001 — any torn artifact
            print(f"WARNING: checkpoint at {path} is unreadable "
                  f"({type(e).__name__}: {e}); falling back to the "
                  f"previous complete checkpoint", flush=True)
            continue
        if it is not None and intended is not None and it < intended:
            print(f"WARNING: resumed from OLDER checkpoint {path} — the "
                  f"newer one(s) were torn or corrupt (a preemption "
                  f"mid-save?); training replays from iteration "
                  f"{out[3]}", flush=True)
        out[2]["loaded_path"] = path
        return out

    print(f"WARNING: no loadable checkpoint in {load_dir} "
          f"({len(candidates)} candidate(s), all torn/corrupt); "
          f"starting from scratch", flush=True)
    return None
