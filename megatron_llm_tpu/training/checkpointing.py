"""Checkpoint save/load — orbax/tensorstore, mesh-shape independent.

Parity target: ref megatron/checkpointing.py — iteration-numbered
directories, a `latest_checkpointed_iteration.txt` tracker (:170),
`--finetune` semantics (reset iteration, skip optim/rng, :583-625),
arg cross-checking (:35-66), rng state for bitwise resume (:217-240).

TPU-first differences: one orbax checkpoint holds the whole (sharded)
params/optimizer tree keyed by logical names — tensorstore reshards on load
under any mesh shape, which deletes the entire reason the reference needs
tools/checkpoint_util.py's tp/pp re-partitioner (SURVEY.md §5). Layout:

    <save>/iter_0000100/{model,optim,meta}   (orbax composite)
    <save>/latest_checkpointed_iteration.txt
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

TRACKER_FILENAME = "latest_checkpointed_iteration.txt"


def checkpoint_dir(save_dir: str, iteration: int, release: bool = False) -> str:
    """ref: get_checkpoint_name (checkpointing.py:77-96) directory level."""
    name = "release" if release else f"iter_{iteration:07d}"
    return os.path.join(save_dir, name)


def read_tracker(load_dir: str) -> Tuple[Optional[int], bool]:
    """ref: read_metadata (checkpointing.py:160-216)."""
    path = os.path.join(load_dir, TRACKER_FILENAME)
    if not os.path.isfile(path):
        return None, False
    with open(path) as f:
        raw = f.read().strip()
    if raw == "release":
        return None, True
    return int(raw), False


def _write_tracker(save_dir: str, iteration: int, release: bool = False) -> None:
    with open(os.path.join(save_dir, TRACKER_FILENAME), "w") as f:
        f.write("release" if release else str(iteration))


def _config_meta(model_cfg) -> dict:
    d = dataclasses.asdict(model_cfg)
    return {k: (str(v) if not isinstance(v, (int, float, bool, str, type(None), list, tuple)) else v)
            for k, v in d.items()}


def check_checkpoint_args(saved: dict, model_cfg) -> None:
    """ref: check_checkpoint_args (checkpointing.py:35-66) — error on
    architecture mismatch."""
    current = _config_meta(model_cfg)
    critical = (
        "num_layers", "hidden_size", "num_attention_heads",
        "num_attention_heads_kv", "ffn_hidden_size", "padded_vocab_size",
        "position_embedding_type", "glu_activation", "use_rms_norm",
        "use_bias", "tie_embed_logits", "parallel_attn", "parallel_layernorm",
    )
    for k in critical:
        if k in saved and saved[k] != current[k]:
            raise ValueError(
                f"checkpoint/config mismatch for {k}: "
                f"checkpoint has {saved[k]!r}, config has {current[k]!r}"
            )


def save_checkpoint(
    save_dir: str,
    iteration: int,
    params: Any,
    opt_state: Any = None,
    model_cfg=None,
    scheduler_state: Optional[dict] = None,
    consumed_train_samples: int = 0,
    rng_key: Optional[jax.Array] = None,
    extra_meta: Optional[dict] = None,
    release: bool = False,
) -> str:
    """ref: save_checkpoint (checkpointing.py:243-338). `release=True`
    writes the converter layout (ref: "release" naming, checkpointing.py:93)."""
    save_dir = os.path.abspath(save_dir)  # orbax requires absolute paths
    path = checkpoint_dir(save_dir, iteration, release=release)
    os.makedirs(save_dir, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "model"), params, force=True)
    if opt_state is not None:
        ckptr.save(
            os.path.join(path, "optim"),
            {"step": opt_state.step, "m": opt_state.m,
             **({"v": opt_state.v} if opt_state.v is not None else {}),
             **({"scaler": opt_state.scaler}
                if getattr(opt_state, "scaler", None) else {})},
            force=True,
        )
    meta = {
        "iteration": iteration,
        "consumed_train_samples": consumed_train_samples,
        "scheduler": scheduler_state or {},
        "config": _config_meta(model_cfg) if model_cfg is not None else {},
        "rng_key": np.asarray(jax.random.key_data(rng_key)).tolist()
        if rng_key is not None else None,
        "checkpoint_version": 3.0,
    }
    meta.update(extra_meta or {})
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    ckptr.wait_until_finished()
    _write_tracker(save_dir, iteration, release=release)
    return path


# The ARCHITECTURE fields --use_checkpoint_args may overlay — exactly the
# check_checkpoint_args critical set plus the shape-determining extras.
# Training knobs (dropout, recompute, flash, seq_length, ...) stay with
# the CLI, matching the reference's _set_arg force-list
# (ref: load_args_from_checkpoint checkpointing.py:506-560).
_CHECKPOINT_ARCH_FIELDS = (
    "num_layers", "hidden_size", "num_attention_heads",
    "num_attention_heads_kv", "kv_channels", "ffn_hidden_size",
    "padded_vocab_size", "position_embedding_type", "glu_activation",
    "hidden_act", "use_rms_norm", "use_bias", "tie_embed_logits",
    "parallel_attn", "parallel_layernorm", "use_post_ln",
    "layernorm_epsilon", "rope_theta", "rope_scaling_factor",
    "max_position_embeddings", "num_tokentypes", "add_binary_head",
)


def load_model_config_from_checkpoint(load_dir: str, mcfg):
    """Overlay the architecture recorded in a checkpoint's meta.json onto
    `mcfg` (ref: load_args_from_checkpoint checkpointing.py:476-560 +
    --use_checkpoint_args). Only the architecture fields listed above are
    taken (training knobs keep their CLI values); None round-trips.
    Returns the updated config, or the input unchanged when no
    checkpoint/meta exists."""
    iteration, release = read_tracker(load_dir)
    if iteration is None and not release:
        return mcfg
    meta_path = os.path.join(
        checkpoint_dir(load_dir, iteration or 0, release=release),
        "meta.json",
    )
    if not os.path.exists(meta_path):
        return mcfg
    with open(meta_path) as f:
        saved = json.load(f).get("config", {})
    updates = {}
    for name in _CHECKPOINT_ARCH_FIELDS:
        if name not in saved or not hasattr(mcfg, name):
            continue
        val = saved[name]
        cur = getattr(mcfg, name)
        if not isinstance(val, (int, float, bool, str, type(None))):
            continue
        if val is None or cur is None:
            if val != cur:
                updates[name] = val
        elif val != cur:
            updates[name] = type(cur)(val)
    if updates:
        print(f" > using checkpoint args from {meta_path}: "
              f"{sorted(updates)}", flush=True)
        mcfg = dataclasses.replace(mcfg, **updates)
    return mcfg


def load_checkpoint(
    load_dir: str,
    params_template: Any,
    opt_state_template: Any = None,
    model_cfg=None,
    finetune: bool = False,
    no_load_optim: bool = False,
    no_load_rng: bool = False,
    iteration: Optional[int] = None,
):
    """ref: load_checkpoint (checkpointing.py:561-730).

    Templates are abstract (jax.eval_shape / ShapeDtypeStruct with sharding)
    or concrete trees; orbax restores into the template's shardings, so the
    same checkpoint loads under any mesh. Returns
    (params, opt_state|None, meta, iteration).
    """
    load_dir = os.path.abspath(load_dir)  # orbax requires absolute paths
    release = False
    if iteration is None:
        iteration, release = read_tracker(load_dir)
        if iteration is None and not release:
            return None  # no checkpoint (ref returns 0 + warns)
        path = checkpoint_dir(load_dir, iteration or 0, release=release)
    else:
        path = checkpoint_dir(load_dir, iteration)

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if model_cfg is not None and meta.get("config"):
        check_checkpoint_args(meta["config"], model_cfg)

    ckptr = ocp.StandardCheckpointer()
    abstract_params = jax.tree.map(ocp.utils.to_shape_dtype_struct, params_template)
    params = ckptr.restore(os.path.join(path, "model"), abstract_params)

    # release checkpoints (converter output) carry weights only: load like
    # --finetune — no optimizer/rng, iteration 0 (ref: checkpointing.py:583-625,
    # release naming :93)
    opt_state = None
    if (opt_state_template is not None and not finetune and not no_load_optim
            and not release):
        from megatron_llm_tpu.optimizer.optimizer import OptimizerState

        tmpl = {"step": opt_state_template.step, "m": opt_state_template.m}
        if opt_state_template.v is not None:
            tmpl["v"] = opt_state_template.v
        if getattr(opt_state_template, "scaler", None):
            tmpl["scaler"] = opt_state_template.scaler
        abstract_opt = jax.tree.map(ocp.utils.to_shape_dtype_struct, tmpl)
        restored = ckptr.restore(os.path.join(path, "optim"), abstract_opt)
        opt_state = OptimizerState(
            step=restored["step"], m=restored["m"], v=restored.get("v"),
            scaler=restored.get("scaler"),
        )

    # --finetune resets iteration and skips optim/rng (ref :583-625)
    out_iteration = 0 if (finetune or release) else meta["iteration"]
    if finetune or no_load_rng or release:
        meta = dict(meta)
        meta["rng_key"] = None
    return params, opt_state, meta, out_iteration
