"""WandB logging shim (ref: megatron/wandb_logger.py:13-172).

Duck-types the tensorboard SummaryWriter interface (`add_scalar`,
`add_text`, `flush`) so the trainer logs to either or both; batches values
and flushes on demand like the reference's `flush_all` (training.py:706-708).
Gated: if wandb isn't importable or configured, becomes a no-op.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class WandBConfig:
    """ref: WandBConfig (wandb_logger.py:13-40) + the --wandb_id/
    --wandb_resume/--wandb_api_key CLI knobs (ref arguments.py:512-529)."""

    project: str = "megatron_llm_tpu"
    name: Optional[str] = None
    entity: Optional[str] = None
    mode: str = "offline"
    id: Optional[str] = None
    resume: bool = False
    api_key: Optional[str] = None


class WandbTBShim:
    def __init__(self, tb_writer=None, config: Optional[WandBConfig] = None):
        self._tb = tb_writer
        self._pending: dict = {}
        self._run = None
        cfg = config or WandBConfig()
        try:
            import wandb

            if cfg.api_key:
                import os

                os.environ.setdefault("WANDB_API_KEY", cfg.api_key)
            self._run = wandb.init(
                project=cfg.project, name=cfg.name, entity=cfg.entity,
                mode=cfg.mode, id=cfg.id,
                resume="must" if cfg.resume else None,
            )
        except Exception:
            self._run = None

    def add_scalar(self, name: str, value, iteration: int):
        if self._tb is not None:
            self._tb.add_scalar(name, value, iteration)
        self._pending.setdefault(iteration, {})[name] = value

    def add_text(self, name: str, text: str, iteration: int = 0):
        if self._tb is not None:
            self._tb.add_text(name, text, iteration)

    def log_run_metadata(self, metadata: dict):
        """One-shot run facts (active remat policy, compiled per-device
        temp/args bytes, ...) — lands in the wandb run CONFIG, so runs are
        filterable/groupable by it in the UI, not buried in a scalar
        stream. (The tensorboard copy arrives separately via the timers'
        gauge ride-along — no mirroring here, or it would land twice.)"""
        if self._run is not None:
            try:
                self._run.config.update(metadata, allow_val_change=True)
            except Exception:
                pass

    def flush(self):
        if self._run is not None:
            for it in sorted(self._pending):
                self._run.log(self._pending[it], step=it)
        self._pending.clear()
        if self._tb is not None:
            self._tb.flush()
