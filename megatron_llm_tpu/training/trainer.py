"""The training runtime: setup + train loop.

Parity target: ref megatron/training.py — `pretrain` (:54), model/optimizer
setup (:197-390), `_train` loop (:639-752) with logging (:452-626), eval
(:754-853), save-interval / signal / duration exits, and data-iterator
construction with consumed-samples resume (:855-939).

Single-controller JAX structure: one process drives the whole mesh; the
"data iterator broadcast" machinery of the reference (tp-rank-0 loads,
broadcast to others, training.py:871-915) disappears — the host feeds
globally-sharded batches directly.
"""

from __future__ import annotations

import os
import signal as _signal
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.analysis.contracts import record_variant
from megatron_llm_tpu.config import ModelConfig, ParallelConfig, TrainConfig
from megatron_llm_tpu.optimizer import (
    OptimizerParamScheduler,
    init_optimizer_state,
)
from megatron_llm_tpu.optimizer.optimizer import OptimizerState
from megatron_llm_tpu.parallel.mesh import get_context
from megatron_llm_tpu.parallel.sharding import (
    optimizer_state_specs,
    param_specs,
)
from megatron_llm_tpu.training.checkpointing import (
    CheckpointManager,
    load_checkpoint,
)
from megatron_llm_tpu.training.microbatches import build_num_microbatches_calculator
from megatron_llm_tpu.training.timers import Timers
from megatron_llm_tpu.training.train_step import make_train_step
from megatron_llm_tpu.training.watchdog import LossWatchdog
from megatron_llm_tpu.utils.masks import get_ltor_masks_and_position_ids


class SignalHandler:
    """ref: dist_signal_handler.py:50-80 — latch SIGTERM, checkpoint+exit."""

    def __init__(self, sig=_signal.SIGTERM):
        self.triggered = False
        try:
            self._prev = _signal.signal(sig, self._handle)
        except ValueError:  # not main thread
            self._prev = None

    def _handle(self, signum, frame):
        self.triggered = True

    def signals_received(self) -> bool:
        return self.triggered


def get_batch(text: np.ndarray, eod_token=None, reset_position_ids=False,
              reset_attention_mask=False, eod_mask_loss=False,
              packed_doc_starts=False):
    """(num_micro, b, seq+1) 'text' -> model inputs
    (ref: finetune.py get_batch :65-81 + utils.get_ltor_masks_and_position_ids).

    `packed_doc_starts`: emit the --reset_attention_mask mask as the O(s)
    {"doc_start"} form instead of a dense (s, s) tensor — required under
    context parallelism, where the dense form would force a full-sequence
    gather (models/attention.py routes doc_start through ring attention
    with the sequence still sharded)."""
    tokens = text[:, :, :-1]
    labels = text[:, :, 1:]
    n, b, s = tokens.shape
    flat = jnp.asarray(tokens.reshape(n * b, s))
    attn_mask, loss_mask, position_ids = get_ltor_masks_and_position_ids(
        flat, eod_token,
        reset_position_ids,
        reset_attention_mask and not packed_doc_starts,
        eod_mask_loss,
    )
    batch = {
        "tokens": jnp.asarray(tokens),
        "labels": jnp.asarray(labels),
        "loss_mask": loss_mask.reshape(n, b, s),
        "position_ids": position_ids.reshape(n, b, s),
    }
    if reset_attention_mask and packed_doc_starts:
        from megatron_llm_tpu.utils.masks import get_document_starts

        batch["attention_mask"] = {
            "doc_start": get_document_starts(flat, eod_token)
            .reshape(n, b, s)
        }
        return batch
    if attn_mask is not None:
        batch["attention_mask"] = attn_mask.reshape(n, b, 1, s, s)
    return batch


@dataclass
class TrainState:
    params: Any
    opt_state: OptimizerState
    iteration: int = 0
    consumed_train_samples: int = 0


class Trainer:
    """Owns setup + the loop. `pretrain()` below is the one-call form."""

    def __init__(
        self,
        model,
        tcfg: TrainConfig,
        pcfg: ParallelConfig,
        train_data_iterator: Optional[Iterable] = None,
        valid_data_iterator: Optional[Iterable] = None,
        eod_token: Optional[int] = None,
        reset_position_ids: bool = False,
        reset_attention_mask: bool = False,
        eod_mask_loss: bool = False,
        batch_builder=None,
    ):
        self.model = model
        self.cfg: ModelConfig = model.cfg
        self.tcfg = tcfg
        self.pcfg = pcfg
        self.train_data_iterator = train_data_iterator
        self.valid_data_iterator = valid_data_iterator
        # raw loader batch -> model-loss kwargs dict; None = GPT get_batch
        # (how pretrain_bert/pretrain_t5 reuse this loop with their own
        # multi-field samples, ref: each entry point's get_batch)
        self.batch_builder = batch_builder
        self.eod_token = eod_token
        self.reset_position_ids = reset_position_ids
        self.reset_attention_mask = reset_attention_mask
        self.eod_mask_loss = eod_mask_loss
        # flight-recorder telemetry (ISSUE 13): the tracer is enabled
        # only with --trace_dir (Chrome trace JSON exported at the end
        # of train(); the named timers double as its spans); the flight
        # recorder is ALWAYS on — a bounded ring of per-step events +
        # watchdog/checkpoint lifecycle, auto-dumped on watchdog
        # rollback and the SIGTERM emergency save (into
        # --flight_record_dir, default the --save dir). Emission is
        # host bookkeeping only: telemetry-on steps are bitwise
        # telemetry-off (tests/test_telemetry.py pins it).
        from megatron_llm_tpu.telemetry import (
            NULL_TRACER,
            FlightRecorder,
            GoodputLedger,
            Histogram,
            PerfSentinel,
            SpanTracer,
            detect_chip,
        )

        self.tracer = (SpanTracer(enabled=True) if tcfg.trace_dir
                       else NULL_TRACER)
        self.recorder = FlightRecorder(tcfg.flight_recorder_size)
        self._step_ms_hist = Histogram(
            "train_step_ms", help_text="wall ms per optimizer step "
            "(dispatch + loss fetch)")
        # goodput & device-cost accounting (ISSUE 15): the ledger is
        # ALWAYS on (pure host float adds — ledger-on training is
        # bitwise ledger-off by construction); the cost registry is
        # opt-in (mint-time capture pays one extra AOT compile per
        # step specialization); the perf sentinel is armed by
        # --perf_sentinel_ksigma > 0 and shares the watchdog's
        # median+MAD machinery, pointed at step_ms.
        self.ledger = GoodputLedger()
        self.chip = detect_chip(override=tcfg.chip_spec)
        self.costs = None
        if tcfg.device_cost_registry:
            from megatron_llm_tpu.telemetry import CostRegistry

            self.costs = CostRegistry(chip=self.chip, owner=self).attach()
        self.sentinel = PerfSentinel(
            k_sigma=tcfg.perf_sentinel_ksigma,
            window=max(tcfg.perf_sentinel_window, 4),
            patience=max(tcfg.perf_sentinel_patience, 1),
            recorder=self.recorder, name="train_step_ms")
        self._last_step_minted = False
        self._last_num_micro: Optional[int] = None
        self.timers = Timers(tcfg.timing_log_level, tcfg.timing_log_option,
                             tracer=self.tracer)
        self._n_params = 0  # set in setup(); enables the TFLOP/s log field
        self._trace_active = False
        self._run_facts_logged = False
        self.ctx = get_context()
        self._eval_step_fn = None

        self.num_microbatches_calc = build_num_microbatches_calculator(
            tcfg.global_batch_size,
            tcfg.micro_batch_size,
            pcfg.data_parallel_size,
            tcfg.rampup_batch_size,
        )

        # sample-based runs (ref: --train_samples, training.py:120-141):
        # the scheduler's step unit becomes SAMPLES — each iteration
        # advances it by that iteration's global batch size, so batch-size
        # rampup stretches warmup/decay in real data consumed, exactly as
        # the reference's increment=get_current_global_batch_size().
        self._samples_mode = tcfg.train_samples is not None
        if self._samples_mode:
            decay_steps = tcfg.lr_decay_samples or tcfg.train_samples
            warmup = tcfg.lr_warmup_samples
            wd_incr_steps = tcfg.train_samples
        else:
            decay_steps = tcfg.lr_decay_iters or tcfg.train_iters
            warmup = tcfg.lr_warmup_iters
            wd_incr_steps = tcfg.train_iters
        if tcfg.lr_warmup_fraction is not None and decay_steps:
            # ref: validate_args derives warmup from the effective decay span
            warmup = int(tcfg.lr_warmup_fraction * decay_steps)
        self.scheduler = OptimizerParamScheduler(
            max_lr=tcfg.lr,
            min_lr=tcfg.min_lr,
            lr_warmup_steps=warmup,
            lr_decay_steps=decay_steps,
            lr_decay_style=tcfg.lr_decay_style,
            start_wd=tcfg.start_weight_decay
            if tcfg.start_weight_decay is not None else tcfg.weight_decay,
            end_wd=tcfg.end_weight_decay
            if tcfg.end_weight_decay is not None else tcfg.weight_decay,
            wd_incr_steps=wd_incr_steps,
            wd_incr_style=tcfg.weight_decay_incr_style,
            use_checkpoint_opt_param_scheduler=tcfg.use_checkpoint_opt_param_scheduler,
            override_opt_param_scheduler=tcfg.override_opt_param_scheduler,
        )
        self.signal_handler = (
            SignalHandler() if tcfg.exit_signal_handler else None
        )
        # fault tolerance (ISSUE 5): the async checkpoint writer is
        # created lazily on first save (tcfg.save may be None), the loss
        # watchdog always exists — with ksigma/patience at 0 it only
        # blocks NaN/inf losses from reaching the weights (the in-step
        # skip gate) and counts them.
        self._ckpt_manager: Optional[CheckpointManager] = None
        self._loaded_ckpt_path: Optional[str] = None
        self.watchdog = LossWatchdog(
            k_sigma=tcfg.loss_watchdog_ksigma,
            window=max(tcfg.loss_watchdog_window, 4),
            patience=tcfg.spike_rollback_patience,
            recorder=self.recorder,
        )
        self._dropout_base_rng: Optional[jax.Array] = None
        self._autoresume = None
        if tcfg.autoresume_file:
            from megatron_llm_tpu.parallel.multihost import AutoResume

            self._autoresume = AutoResume(tcfg.autoresume_file,
                                          tcfg.autoresume_interval)
        self._train_steps: dict = {}  # num_microbatches -> jitted step
        self._tb_writer = None
        if tcfg.tensorboard_dir:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb_writer = SummaryWriter(
                    tcfg.tensorboard_dir, max_queue=tcfg.tensorboard_queue_size
                )
            except Exception:
                self._tb_writer = None
        if tcfg.wandb_logger:
            try:
                from megatron_llm_tpu.training.wandb_logger import (
                    WandBConfig,
                    WandbTBShim,
                )

                wcfg = WandBConfig(
                    project=tcfg.wandb_project or "megatron_llm_tpu",
                    entity=tcfg.wandb_entity,
                    id=tcfg.wandb_id,
                    resume=tcfg.wandb_resume,
                    api_key=tcfg.wandb_api_key,
                )
                self._tb_writer = WandbTBShim(self._tb_writer, wcfg)
            except Exception:
                pass
        if self._tb_writer is not None and tcfg.log_world_size_to_tensorboard:
            # ref: --log_world_size_to_tensorboard (training.py:590)
            self._tb_writer.add_scalar("world-size", len(jax.devices()), 0)

    # ------------------------------------------------------------------
    def setup(self, rng: Optional[jax.Array] = None) -> TrainState:
        """Build (sharded) params + optimizer state; resume from checkpoint
        (ref: _setup_model_and_optimizer training.py:351-390)."""
        rng = rng if rng is not None else jax.random.key(self.tcfg.seed)
        self.timers("model-and-optimizer-setup").start()
        if self.ctx is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self.ctx.mesh
            tmpl = jax.eval_shape(self.model.init, rng)
            if self.pcfg.pipeline_parallel_size > 1:
                from megatron_llm_tpu.parallel.pipeline import (
                    pipeline_param_specs as param_specs_fn,
                )
            else:
                param_specs_fn = param_specs
            pspecs = param_specs_fn(self.cfg, tmpl)
            psh = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                               is_leaf=lambda x: isinstance(x, P))
            params = jax.jit(self.model.init, out_shardings=psh)(rng)
            ospecs = optimizer_state_specs(
                self.cfg, tmpl, self.pcfg.data_parallel_size,
                self.pcfg.use_distributed_optimizer, base_specs=pspecs,
                # m/v follow the grad layout: --overlap_grad_reduce
                # shards stacked-layer leaves within a layer (ISSUE 12)
                overlap_grads=self.pcfg.overlap_grad_reduce,
            )
            osh = jax.tree.map(lambda s: NamedSharding(mesh, s), ospecs,
                               is_leaf=lambda x: isinstance(x, P))
            from megatron_llm_tpu.optimizer.optimizer import get_grad_scaler

            sc = get_grad_scaler(self.tcfg)
            sc_sh = (jax.tree.map(lambda _: NamedSharding(mesh, P()),
                                  sc.init_state())
                     if sc is not None else None)
            opt_state = jax.jit(
                lambda p: init_optimizer_state(p, self.tcfg),
                out_shardings=OptimizerState(
                    step=NamedSharding(mesh, P()), m=osh, v=osh,
                    scaler=sc_sh),
            )(params)
        else:
            params = self.model.init(rng)
            opt_state = init_optimizer_state(params, self.tcfg)
        self.timers("model-and-optimizer-setup").stop()

        self._n_params = sum(int(np.prod(p.shape))
                             for p in jax.tree.leaves(params))
        state = TrainState(params=params, opt_state=opt_state)
        if self.tcfg.load:
            loaded = load_checkpoint(
                self.tcfg.load, params, opt_state, self.cfg,
                finetune=self.tcfg.finetune,
                no_load_optim=self.tcfg.no_load_optim,
                no_load_rng=self.tcfg.no_load_rng,
            )
            if loaded is not None:
                params, opt_state_l, meta, iteration = loaded
                state = TrainState(
                    params=params,
                    opt_state=opt_state_l if opt_state_l is not None else opt_state,
                    iteration=iteration,
                    consumed_train_samples=0 if self.tcfg.finetune
                    else meta.get("consumed_train_samples", 0),
                )
                if meta.get("scheduler") and not self.tcfg.finetune:
                    self.scheduler.load_state_dict(meta["scheduler"])
                # retention GC must never delete the checkpoint a resume
                # read from (checkpointing.py CheckpointManager.protect)
                self._loaded_ckpt_path = meta.get("loaded_path")
                print(f"loaded checkpoint from {self.tcfg.load} at iteration "
                      f"{state.iteration}", flush=True)
        return state

    def _get_step_fn(self, num_microbatches: int):
        if num_microbatches not in self._train_steps:
            import dataclasses as _dc

            pcfg = _dc.replace(self.pcfg, num_microbatches=num_microbatches)
            if pcfg.pipeline_parallel_size > 1:
                assert self.ctx is not None, "pp>1 requires an installed mesh"
                from megatron_llm_tpu.parallel.pipeline import (
                    make_pipelined_train_step,
                )

                fn = make_pipelined_train_step(
                    self.model, self.tcfg, pcfg, self.ctx,
                    contract_key=num_microbatches, contract_owner=self,
                )
            else:
                fn = make_train_step(
                    self.model, self.tcfg, pcfg,
                    batch_builder=self.batch_builder,
                    contract_key=num_microbatches, contract_owner=self,
                )
            # ONE jit site serves both branches:
            # graft-contract: train.step (the pp=1 make_train_step above)
            # graft-contract: train.pipeline_step (the pp>1 branch)
            self._train_steps[num_microbatches] = jax.jit(
                fn, donate_argnums=(0, 1)
            )
        return self._train_steps[num_microbatches]

    # ------------------------------------------------------------------
    def _log_run_facts(self, step_fn, lower_args):
        """Once, at step 0: the active remat policy — and, under
        --log_memory_to_tensorboard, the compiled per-device temp/args
        bytes of the exact train step — so a WandB/tensorboard perf
        trajectory is attributable to the memory/FLOP trade in effect
        (the step-0 analogue of bench.py's remat sweep). The memory
        analysis is opt-in because on this JAX line .lower().compile()
        does not reuse the jit call cache: it pays one extra full compile
        of the train step."""
        self._run_facts_logged = True
        facts = {"remat-policy": self.cfg.resolved_remat_policy}
        if self.pcfg.pipeline_parallel_size > 1:
            facts["pipeline-remat"] = self.pcfg.resolved_pipeline_remat
        if self.pcfg.use_distributed_optimizer:
            # ZeRO-1 facts (ISSUE 10): which decomposition is active,
            # the per-device optimizer-state bytes actually committed
            # (read from the LIVE opt-state shardings, not the specs),
            # and the analytic dp gradient-wire bytes per step — the
            # numbers the llama7b-v5p64 sizing math is made of.
            from megatron_llm_tpu.optimizer.zero1 import (
                build_overlap_plan,
                build_zero1_plan,
                explicit_zero1_supported,
            )

            opt_state = lower_args[1]
            facts["zero1-path"] = (
                "explicit-rs" if explicit_zero1_supported(
                    self.model, self.pcfg, self.ctx,
                    batch_builder=self.batch_builder)
                else "gspmd-spec")
            if self.pcfg.quantized_grad_reduce:
                facts["zero1-quantized-reduce"] = True
            overlap_on = [
                n for n, f in (("grads", self.pcfg.overlap_grad_reduce),
                               ("gather", self.pcfg.overlap_param_gather))
                if f]
            if overlap_on:
                facts["zero1-overlap"] = "+".join(overlap_on)
            try:
                per_dev = 0
                for leaf in jax.tree.leaves(
                        (opt_state.m, opt_state.v)):
                    shard = leaf.sharding.shard_shape(leaf.shape)
                    per_dev += int(np.prod(shard)) * leaf.dtype.itemsize
                facts["opt-state-bytes-device"] = per_dev
            except Exception:
                pass
            if facts["zero1-path"] == "explicit-rs":
                # the SAME plan flavor the step built: bucket counts and
                # per-bucket wire bytes must describe the schedule
                # actually running (ISSUE 12)
                build = (build_overlap_plan
                         if self.pcfg.overlap_grad_reduce
                         else build_zero1_plan)
                plan = build(
                    self.cfg, lower_args[0],
                    self.pcfg.data_parallel_size,
                    bucket_mb=self.pcfg.grad_rs_bucket_mb)
                params_bytes = sum(
                    int(np.prod(l.shape)) * l.dtype.itemsize
                    for l in jax.tree.leaves(lower_args[0]))
                num_micro = jax.tree.leaves(lower_args[2])[0].shape[0]
                facts["grad-comm-bytes-step"] = (
                    plan.comm_bytes_per_reduce(
                        self.pcfg.quantized_grad_reduce)
                    * num_micro
                    + params_bytes  # the param all-gather leg
                )
                bucket_bytes = plan.bucket_comm_bytes(
                    self.pcfg.quantized_grad_reduce)
                facts["grad-rs-buckets"] = len(bucket_bytes)
                # per-issue-point wire bytes so bucket sizing can be
                # tuned against the overlap window (--grad_rs_bucket_mb)
                facts["grad-rs-bucket-bytes"] = list(bucket_bytes)
        # the opt-in relower (--log_memory_to_tensorboard — it pays one
        # extra full compile, see docstring): memory analysis rides it
        # as before; on overlap runs the same compiled text also yields
        # the measured `grad-comm-overlap-pairs` gauge (the async
        # -start/-done pair count of the exact step, analysis/overlap.py
        # — a measured 0 on backends without async collectives)
        want_overlap_report = (
            self.tcfg.log_memory_to_tensorboard
            and (self.pcfg.overlap_grad_reduce
                 or self.pcfg.overlap_param_gather))
        want_memory = (self._tb_writer is not None
                       and self.tcfg.log_memory_to_tensorboard)
        if want_memory or want_overlap_report:
            try:
                compiled = step_fn.lower(*lower_args).compile()
                if want_memory:
                    mem = compiled.memory_analysis()
                    facts["compiled-temp-bytes"] = int(
                        mem.temp_size_in_bytes)
                    facts["compiled-args-bytes"] = int(
                        mem.argument_size_in_bytes
                    )
                if want_overlap_report:
                    from megatron_llm_tpu.analysis.overlap import (
                        collective_overlap_report,
                    )

                    rep = collective_overlap_report(compiled.as_text())
                    facts["grad-comm-overlap-pairs"] = rep.async_pairs
                    if rep.async_pairs:
                        facts["grad-comm-overlap-max-in-flight"] = \
                            rep.max_in_flight
            except Exception as e:
                print(f"step-0 memory analysis unavailable: {e}",
                      flush=True)
        for k, v in facts.items():
            self.timers.gauge(k, v)
        self.timers.log([])  # surfaces the new gauges once, right now
        if self._tb_writer is not None:
            # tensorboard via the timers' once-per-channel gauge ride-along;
            # the wandb shim additionally lands them in the run CONFIG
            self.timers.write([], self._tb_writer, 0)
            if hasattr(self._tb_writer, "log_run_metadata"):
                self._tb_writer.log_run_metadata(facts)

    def train_step(self, state: TrainState, text: np.ndarray, dropout_rng=None):
        """One optimizer step over a global batch 'text'
        (num_micro, mbs*dp, seq+1) array, or a dict of such arrays when a
        batch_builder is installed (ref: train_step training.py:391-450)."""
        if self.batch_builder is not None:
            batch = self.batch_builder(text)
            num_micro = jax.tree.leaves(batch)[0].shape[0]
        else:
            num_micro = text.shape[0]
            batch = get_batch(
                text, self.eod_token, self.reset_position_ids,
                self.reset_attention_mask, self.eod_mask_loss,
                # under cp the dense mask would gather the full sequence;
                # ship the O(s) doc-start form through ring attention
                packed_doc_starts=self.ctx is not None and self.ctx.cp > 1,
            )
            if (self.pcfg.pipeline_parallel_size > 1
                    and "attention_mask" in batch):
                raise ValueError(
                    "pp>1 training does not support "
                    "--reset_attention_mask (the pipelined loss has no "
                    "attention-mask path); drop the flag or train with "
                    "pp=1"
                )
        lr, wd = self.scheduler.get_lr(), self.scheduler.get_wd()
        if self.ctx is not None and jax.process_count() > 1:
            # per-process rows -> global arrays sharded over `data`
            # (ref analogue: each rank's sampler loads only its chunk)
            from megatron_llm_tpu.parallel.multihost import globalize_batch

            batch = globalize_batch(batch, self.ctx)
        # a fresh mint means this call pays trace+compile: the goodput
        # ledger books its wall under "compile", and (registry on) the
        # mint's cost is captured right after the call below
        minted = num_micro not in self._train_steps
        self._last_step_minted = minted
        # which specialization this step ran: the MFU gauge's registry
        # lookup must read THIS mint's record, not whichever record was
        # captured first (a rampup run holds several)
        self._last_num_micro = num_micro
        step_fn = self._get_step_fn(num_micro)
        first_step = state.iteration == 0 and not self._run_facts_logged
        # the loss watchdog's in-step skip gate: +inf until the window
        # has history (or with spike detection off) — NaN/inf losses
        # still skip. Always passed, so there is ONE trace either way.
        spike_thr = jnp.float32(self.watchdog.threshold())
        params, opt_state, stats = step_fn(
            state.params, state.opt_state, batch,
            jnp.float32(lr), jnp.float32(wd), dropout_rng, spike_thr,
        )
        state.params = params
        state.opt_state = opt_state
        if minted and self.costs is not None:
            # compiled-cost capture at MINT time (ISSUE 15): once per
            # step specialization, with the post-step params/opt trees
            # (same avals; the pre-step buffers were donated). Pays one
            # extra AOT compile — the documented price of the opt-in.
            self.costs.capture(
                "train.pipeline_step"
                if self.pcfg.pipeline_parallel_size > 1 else "train.step",
                num_micro, step_fn,
                (params, opt_state, batch, jnp.float32(lr),
                 jnp.float32(wd), dropout_rng, spike_thr))
        if first_step:
            # AFTER the first execution (avals of the donated args are
            # unchanged, and the opt-in memory relower never races the
            # step's own compile)
            self._log_run_facts(
                step_fn,
                (params, opt_state, batch, jnp.float32(lr),
                 jnp.float32(wd), dropout_rng, spike_thr),
            )
        state.iteration += 1
        mbs_dp = jax.tree.leaves(batch)[0].shape[1]
        # samples mode: the scheduler advances by samples consumed this
        # iteration (ref: training.py increment=get_current_global_batch_size)
        self.scheduler.step(num_micro * mbs_dp if self._samples_mode else 1)
        state.consumed_train_samples += num_micro * mbs_dp
        self.num_microbatches_calc.update(state.consumed_train_samples)
        stats["lr"] = lr
        stats["batch_size"] = num_micro * mbs_dp
        return stats

    def evaluate(self, state: TrainState, max_iters: Optional[int] = None) -> float:
        """ref: evaluate (training.py:754-853). With a batch_builder
        installed (BERT/T5/biencoder), the eval step runs the model's own
        loss kwargs per microbatch instead of the GPT path."""
        if self.valid_data_iterator is None:
            return float("nan")
        if self._eval_step_fn is None:
            if self.pcfg.pipeline_parallel_size > 1 \
                    and self.batch_builder is None:
                # stage-sharded params: eval through the pipelined loss
                # (the non-pipelined path would all-gather every layer).
                # num_micro is derived from the batch shape, so any
                # (num_micro, rows, seq) eval batch works.
                from megatron_llm_tpu.parallel.pipeline import (
                    make_pipelined_loss_fn,
                )

                loss_fn = make_pipelined_loss_fn(
                    self.model, self.pcfg, self.ctx
                )
                record_variant("train.eval_step", "pp", owner=self)

                # graft-contract: train.eval_step
                @jax.jit
                def pp_eval(params, batch):
                    return loss_fn(params, batch)

                self._eval_step_fn = pp_eval
            elif self.batch_builder is not None:
                if self.pcfg.pipeline_parallel_size > 1:
                    print("WARNING: eval with a batch_builder on a pp>1 "
                          "mesh gathers the stage-sharded layers per "
                          "microbatch (encoder models have no pipelined "
                          "loss path)", flush=True)
                model = self.model
                record_variant("train.eval_step", "generic", owner=self)

                # graft-contract: train.eval_step
                @jax.jit
                def generic_eval(params, batch):
                    n = jax.tree.leaves(batch)[0].shape[0]
                    losses = [
                        model.loss(params, deterministic=True,
                                   **jax.tree.map(lambda x: x[i], batch))
                        for i in range(n)
                    ]
                    return sum(losses) / len(losses)

                self._eval_step_fn = generic_eval
            else:
                from megatron_llm_tpu.training.train_step import (
                    make_eval_step,
                )

                # graft-contract: train.eval_step
                self._eval_step_fn = jax.jit(make_eval_step(
                    self.model, contract_key="plain", contract_owner=self))
        eval_step = self._eval_step_fn
        total, count = 0.0, 0
        iters = max_iters if max_iters is not None else self.tcfg.eval_iters
        it = iter(self.valid_data_iterator)
        for _ in range(iters):
            try:
                text = next(it)
            except StopIteration:
                break
            if self.batch_builder is not None:
                batch = self.batch_builder(text)
            elif self.pcfg.pipeline_parallel_size > 1:
                # pipelined eval keeps the (num_micro, rows, seq) axes
                batch = get_batch(text, self.eod_token)
                # the pipelined loss builds its own causal masking and
                # cannot honor per-document reset masks
                assert "attention_mask" not in batch, (
                    "pp>1 eval does not support reset_attention_mask"
                )
            else:
                raw = get_batch(text, self.eod_token)
                batch = jax.tree.map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), raw
                )
            if self.ctx is not None and jax.process_count() > 1:
                from megatron_llm_tpu.parallel.multihost import (
                    globalize_batch,
                )

                # batch_builder AND pipelined eval batches keep the micro
                # axis (rows at 1); the flat GPT eval path has rows at 0
                flat_rows = (self.batch_builder is None
                             and self.pcfg.pipeline_parallel_size == 1)
                batch = globalize_batch(
                    batch, self.ctx, row_axis=0 if flat_rows else 1,
                )
            total += float(eval_step(state.params, batch))
            count += 1
        return total / max(count, 1)

    # ------------------------------------------------------------------
    def _training_log(self, state: TrainState, stats: dict, elapsed: float):
        """ref: training_log (training.py:452-626)."""
        loss = float(stats["loss"])
        gnorm = float(stats["grad_norm"])
        line = (
            f"iteration {state.iteration:8d}/{self.tcfg.train_iters or 0:8d} | "
            f"consumed samples: {state.consumed_train_samples:12d} | "
            f"elapsed time per iteration (ms): {elapsed*1000:.1f} | "
            f"learning rate: {stats['lr']:.3E} | "
            f"global batch size: {stats['batch_size']:5d} | "
            f"lm loss: {loss:.6E} | "
        )
        if "loss_scale" in stats:
            line += f"loss scale: {float(stats['loss_scale']):.1f} | "
        line += f"grad norm: {gnorm:.3f} | "
        if "num_zeros" in stats:
            line += f"num zeros: {int(stats['num_zeros'])} | "
        if "params_norm" in stats:
            line += f"params norm: {float(stats['params_norm']):.3f} | "
        line += f"skipped iterations: {int(stats['skipped'])}"
        # watchdog counters ride the gauge channel, re-armed only when
        # they actually move (a gauge re-set reprints on the next log)
        for name, val in self.watchdog.counters().items():
            if self.timers.gauges().get(name) != val:
                self.timers.gauge(name, val)
        # throughput + achieved model-FLOP/s (the reference logs
        # elapsed-per-iteration only; TFLOP/s makes MFU one division away)
        if self._n_params:
            tok_s = stats["batch_size"] * self.cfg.seq_length / max(elapsed,
                                                                    1e-9)
            tflops = tok_s * 6 * self._n_params / 1e12
            line += (f" | tokens/sec: {tok_s:.1f} | "
                     f"model TFLOP/s: {tflops:.2f}")
        # goodput partition + live MFU/roofline gauges (ISSUE 15): the
        # ledger counters re-set each log interval (cumulative seconds
        # move every step), the MFU/roofline gauges only when a chip
        # spec is known — an MFU against a guessed peak is worse than
        # no gauge (telemetry/chipspec.py)
        for name, val in self.ledger.counters().items():
            self.timers.gauge(name, val)
        self._device_cost_gauges(elapsed, stats["batch_size"])
        print(line, flush=True)
        # timer dump at the log cadence; only per-iteration timers get the
        # log_interval normalizer (one-shot timers like setup/save would be
        # misreported) — ref: timers.log call training.py:618
        self.timers.log(["batch-generator", "train-step"],
                        normalizer=self.tcfg.log_interval)

    def _device_cost_gauges(self, elapsed: float, batch_size: int):
        """Live MFU + per-executable roofline gauges (ISSUE 15).

        train_mfu is the last logged step's achieved fraction of the
        chip peak; train_mfu_effective is the ISSUE formula — step
        FLOPs x productive steps / WALL / peak — i.e. MFU debited for
        every non-productive second the goodput ledger booked. The
        FLOPs numerator is the cost registry's train.step record when
        captured (`--device_cost_registry`), else the analytic
        6N+attention model (telemetry/chipspec.train_flops_per_token) —
        the gauge's `train_mfu_source` names which, because the two are
        different claims (GUIDE: the modeled-FLOPs caveat). Gauges are
        ABSENT without a known chip spec."""
        if self.chip is None or not self._n_params or elapsed <= 0:
            return
        n_dev = self.ctx.mesh.size if self.ctx is not None else 1
        peak = self.chip.peak_flops_for(
            str(self.cfg.compute_dtype)) * n_dev
        # the record of the specialization the logged step ACTUALLY ran
        # (keyed num_microbatches): under batch-size rampup several
        # specializations are captured, and reading an arbitrary one
        # would misstate MFU by the microbatch ratio while claiming the
        # "registry" source
        key = getattr(self, "_last_num_micro", None)
        rec = (self.costs.record("train.step", key)
               or self.costs.record("train.pipeline_step", key)) \
            if self.costs is not None and key is not None else None
        if rec is not None and rec.flops:
            step_flops = rec.flops
            source = "registry"
        else:
            from megatron_llm_tpu.telemetry.chipspec import (
                train_flops_per_token,
            )

            step_flops = train_flops_per_token(
                self._n_params, self.cfg.num_layers,
                self.cfg.hidden_size, self.cfg.seq_length,
            ) * batch_size * self.cfg.seq_length
            source = "analytic"
        self.timers.gauge("train_mfu",
                          round(step_flops / elapsed / peak, 6))
        snap = self.ledger.snapshot()
        if snap["wall_s"] > 0 and snap["productive_steps"]:
            self.timers.gauge(
                "train_mfu_effective",
                round(step_flops * snap["productive_steps"]
                      / snap["wall_s"] / peak, 6))
        self.timers.gauge("train_mfu_source", source)
        self.timers.gauge("chip_spec", self.chip.label())
        if rec is not None and rec.bytes_accessed:
            # per-executable achieved-GB/s roofline: the step's
            # compiled bytes-accessed over its measured wall vs the
            # chip's HBM rate
            gbps = rec.bytes_accessed / elapsed / 1e9
            self.timers.gauge("train_step_achieved_gbps",
                              round(gbps, 1))
            self.timers.gauge(
                "train_step_hbm_frac",
                round(gbps * 1e9 / (self.chip.hbm_bytes_s * n_dev), 4))

    def _tb_log(self, state, stats, elapsed):
        """Tensorboard/wandb scalars — own cadence, independent of the
        console log_interval (ref: training_log gates tb writes on
        --tensorboard_log_interval per iteration, training.py:560-607)."""
        if self._tb_writer is None or (
            state.iteration % max(self.tcfg.tensorboard_log_interval, 1) != 0
        ):
            return
        loss = float(stats["loss"])
        gnorm = float(stats["grad_norm"])
        w = self._tb_writer
        it = state.iteration
        w.add_scalar("lm-loss", loss, it)
        w.add_scalar("learning-rate", stats["lr"], it)
        w.add_scalar("grad-norm", gnorm, it)
        w.add_scalar("batch-size", stats["batch_size"], it)
        if "loss_scale" in stats:
            w.add_scalar("loss-scale", float(stats["loss_scale"]), it)
        if "params_norm" in stats:
            w.add_scalar("params-norm", float(stats["params_norm"]), it)
        if "num_zeros" in stats:
            w.add_scalar("num-zeros", int(stats["num_zeros"]), it)
        if self.tcfg.log_timers_to_tensorboard:
            # ref: --log_timers_to_tensorboard writes iteration-time
            # (training.py:598-600)
            w.add_scalar("iteration-time", elapsed, it)
        # fault-tolerance counters (ISSUE 5): spikes skipped, rollbacks
        # taken, and the async-save stall — the WandB-visible proof the
        # watchdog/async-checkpoint path is doing its job
        w.add_scalar("loss-watchdog-skipped", self.watchdog.skipped, it)
        w.add_scalar("loss-watchdog-rollbacks", self.watchdog.rollbacks, it)
        # goodput cumulative counters (ISSUE 15): the wall-time
        # partition as scalars a dashboard can rate() over, plus the
        # headline fraction; sentinel trips when armed
        snap = self.ledger.snapshot()
        w.add_scalar("goodput-fraction", snap["goodput_fraction"], it)
        for b, v in snap["buckets"].items():
            w.add_scalar(f"goodput-{b}-seconds", v, it)
        if self.sentinel.enabled:
            w.add_scalar("perf-sentinel-trips", self.sentinel.trips, it)
        if self._ckpt_manager is not None and self._ckpt_manager.saves:
            w.add_scalar("ckpt-blocked-ms",
                         self._ckpt_manager.last_blocked_ms, it)
        if self.tcfg.log_memory_to_tensorboard:
            # ref: --log_memory_to_tensorboard (training.py:601-607);
            # here the device allocator's live-bytes gauge
            try:
                ms = jax.local_devices()[0].memory_stats() or {}
                w.add_scalar("mem-bytes-in-use",
                             ms.get("bytes_in_use", 0), it)
            except Exception:
                pass
        if hasattr(w, "flush"):
            # ref: flush_all batching (training.py:706-708)
            w.flush()

    def _get_ckpt_manager(self) -> CheckpointManager:
        if self._ckpt_manager is None:
            self._ckpt_manager = CheckpointManager(
                self.tcfg.save, keep_latest_n=self.tcfg.keep_latest_n,
                async_save=self.tcfg.async_save,
                recorder=self.recorder,
            )
            self._ckpt_manager.protect(self._loaded_ckpt_path)
        return self._ckpt_manager

    def _flight_record_dir(self):
        """Where flight-record artifacts land: --flight_record_dir,
        falling back to the --save dir (the place a postmortem already
        looks); None = in-memory + log-summary only."""
        return self.tcfg.flight_record_dir or self.tcfg.save

    def _save(self, state: TrainState, blocking: bool = False):
        """Interval save: async by default — the loop stalls only for
        the previous save's tail + the device→host copy, surfaced as the
        `ckpt_blocked_ms` gauge. `blocking=True` (exit paths: emergency
        save, final save, rollback prep) additionally waits for the
        commit so the process may die right after."""
        if not self.tcfg.save:
            return
        mgr = self._get_ckpt_manager()
        t_save = time.perf_counter()
        self.timers("save-checkpoint").start()
        mgr.save(
            state.iteration, state.params,
            None if self.tcfg.no_save_optim else state.opt_state,
            self.cfg, self.scheduler.state_dict(),
            state.consumed_train_samples,
            rng_key=self._dropout_base_rng,
        )
        self.timers("save-checkpoint").stop()
        self.timers.gauge("ckpt_blocked_ms", round(mgr.last_blocked_ms, 2))
        # the save's loop stall on the trace timeline, step-correlated
        # (the save-checkpoint timer span carries the full dispatch)
        self.tracer.instant("ckpt_blocked",
                            blocked_ms=round(mgr.last_blocked_ms, 3))
        if blocking:
            mgr.wait_until_finished()
        if self.ledger.started:
            # goodput: the loop's whole save-side stall — dispatch,
            # previous-save tail, and (blocking) the commit wait
            self.ledger.note("checkpoint",
                             time.perf_counter() - t_save)
        print(f"saved checkpoint at iteration {state.iteration} to "
              f"{self.tcfg.save}"
              f"{' (committed)' if blocking else ' (async)'}", flush=True)

    def _rollback(self, state: TrainState) -> bool:
        """Loss-watchdog escalation: reload the last COMPLETE checkpoint
        into the live state and KEEP the data iterator where it is — the
        batches between the checkpoint and now (the poison window) are
        consumed-but-never-trained-on, which is exactly the manual
        restart-and-skip loop of the big-run reports, automated. Returns
        False (and keeps skip-only behavior) when there is nothing to
        roll back to."""
        if not self.tcfg.save:
            print("WARNING: loss watchdog wants a rollback but no --save "
                  "dir is configured; continuing in skip-only mode",
                  flush=True)
            return False
        t_roll = time.perf_counter()
        # the in-flight async save must finalize first: it is newer than
        # anything on disk and about to become the rollback target
        self._get_ckpt_manager().wait_until_finished()
        loaded = load_checkpoint(
            self.tcfg.save, state.params,
            # --no_save_optim checkpoints have no optim dir: don't let
            # the torn-save scan misread every healthy checkpoint as
            # corrupt trying to restore one
            None if self.tcfg.no_save_optim else state.opt_state,
            self.cfg,
            no_load_optim=self.tcfg.no_save_optim
            or self.tcfg.no_load_optim,
        )
        if loaded is None:
            print("WARNING: loss watchdog wants a rollback but no "
                  "complete checkpoint exists yet; continuing in "
                  "skip-only mode", flush=True)
            return False
        params, opt_state, meta, iteration = loaded
        poison = state.iteration - iteration
        state.params = params
        if opt_state is not None:
            state.opt_state = opt_state
        state.iteration = iteration
        # consumed_train_samples is NOT rewound: it is the data
        # position (loaders — and a later crash-resume — restart from
        # it), and the live iterator stays where it is. Rewinding the
        # counter while the iterator kept going would replay the poison
        # window on the next restart — the opposite of fast-forward.
        # The poison batches stay consumed-but-untrained; the scheduler
        # replays its own state from the checkpoint.
        if meta.get("scheduler"):
            self.scheduler.load_state_dict(meta["scheduler"])
        self._get_ckpt_manager().protect(meta.get("loaded_path"))
        self.watchdog.note_rollback(step=iteration + poison,
                                    restored_step=iteration)
        self.tracer.instant("watchdog_rollback", restored_step=iteration,
                            poison_window=poison)
        # flight-recorder postmortem artifact (ISSUE 13): the verdict
        # trail + per-step record that led to this rollback, dumped
        # BEFORE training resumes — the artifact names the failing
        # step range even if the run later dies for another reason
        if self.ledger.started:
            # the rollback's reload/wait stall is watchdog-spent wall
            self.ledger.note("watchdog", time.perf_counter() - t_roll)
        self.recorder.dump(
            self._flight_record_dir(), "watchdog-rollback",
            extra={"restored_step": iteration,
                   "poison_window": poison,
                   "rollback": self.watchdog.rollbacks,
                   "goodput": self.ledger.snapshot()})
        print(f"LOSS WATCHDOG ROLLBACK: reloaded iteration {iteration} "
              f"from {self.tcfg.save}; data iterator fast-forwarded past "
              f"the {poison}-iteration poison window "
              f"(rollback #{self.watchdog.rollbacks})", flush=True)
        return True

    def train(self, state: TrainState) -> TrainState:
        """The loop (ref: _train training.py:639-752)."""
        tcfg = self.tcfg
        assert self.train_data_iterator is not None
        data_iter = iter(self.train_data_iterator)
        start_time = time.time()
        dropout_rng = None
        if self.cfg.hidden_dropout > 0 or self.cfg.attention_dropout > 0:
            dropout_rng = jax.random.key(tcfg.seed + 1)
            # saved in checkpoint meta: resume folds the SAME base key
            # with the restored iteration, so the dropout stream — and
            # therefore the loss trajectory — is bitwise on resume
            self._dropout_base_rng = dropout_rng

        def keep_going():
            if self._samples_mode:
                return state.consumed_train_samples < tcfg.train_samples
            return tcfg.train_iters is None or \
                state.iteration < tcfg.train_iters

        last_log_time = time.time()
        # the goodput wall clock starts with the loop: every second
        # from here lands in exactly one ledger bucket (ISSUE 15)
        self.ledger.start()
        while keep_going():
            # every span this iteration emits (batch-generator,
            # train-step, save-checkpoint via the timers ride-along)
            # carries the step it belongs to — the trace-side half of
            # the rid/step correlation model (ISSUE 13)
            self.tracer.set_context(step=state.iteration + 1)
            self.timers("batch-generator").start()
            t_fetch = time.perf_counter()
            try:
                text = next(data_iter)
            except StopIteration:
                print("data iterator exhausted", flush=True)
                break
            finally:
                self.timers("batch-generator").stop()
                self.ledger.note("data_wait",
                                 time.perf_counter() - t_fetch)
            step_rng = None
            if dropout_rng is not None:
                step_rng = jax.random.fold_in(dropout_rng, state.iteration)
            # device-trace window (ref: --profile nsys window,
            # training.py:687-703; here jax.profiler -> tensorboard)
            if (tcfg.profile and not self._trace_active
                    and state.iteration >= tcfg.profile_step_start
                    and state.iteration < tcfg.profile_step_end):
                jax.profiler.start_trace(
                    tcfg.profile_dir or tcfg.tensorboard_dir or "./profile"
                )
                self._trace_active = True
            t0 = time.time()
            # the whole fused fwd+bwd+optimizer dispatch — the reference's
            # forward-backward/optimizer timer pair collapses into one
            # jitted call here (training.py:431-448)
            self.timers("train-step").start()
            stats = self.train_step(state, text, step_rng)
            loss_val = float(stats["loss"])  # host sync (axon: the real barrier)
            self.timers("train-step").stop()
            stats["loss"] = loss_val
            elapsed = time.time() - t0
            # loss watchdog: a bad step (NaN/inf or >k-sigma spike) was
            # already SKIPPED on device by the spike-threshold gate; the
            # host side counts the streak and escalates to a rollback
            # after `spike_rollback_patience` consecutive bad steps.
            bad = self.watchdog.observe(loss_val, step=state.iteration)
            # goodput classification (ISSUE 15): this step's wall lands
            # in exactly one bucket — a fresh mint paid trace+compile
            # (the first execution rides the compile bucket, the
            # documented semantics), a watchdog-skipped step spent wall
            # the device discarded, everything else is productive.
            bucket = ("compile" if self._last_step_minted
                      else "watchdog" if bad else "productive")
            self.ledger.note(bucket, elapsed)
            # flight-recorder step trail + the step-ms histogram
            # (host floats only — the loss was already fetched above)
            self._step_ms_hist.observe(elapsed * 1e3)
            self.recorder.record("step", step=state.iteration,
                                 loss=loss_val,
                                 ms=round(elapsed * 1e3, 3),
                                 bucket=bucket)
            if self._trace_active and state.iteration >= tcfg.profile_step_end:
                jax.profiler.stop_trace()
                self._trace_active = False

            if bad:
                self.tracer.instant("watchdog_bad", loss=loss_val,
                                    streak=self.watchdog.consecutive_bad)
                print(f"loss watchdog: bad step at iteration "
                      f"{state.iteration} (loss {loss_val:.6E}, "
                      f"threshold {self.watchdog.threshold():.6E}, "
                      f"streak {self.watchdog.consecutive_bad})",
                      flush=True)
                if self.watchdog.should_rollback():
                    self._rollback(state)
            elif bucket == "productive" and self.sentinel.enabled:
                # perf sentinel (ISSUE 15): productive steps only —
                # compile steps would poison the latency baseline the
                # same way a spike would poison the loss window
                if self.sentinel.observe(elapsed * 1e3,
                                         step=state.iteration):
                    self.timers.gauge("perf_sentinel_trips",
                                      self.sentinel.trips)
                    self.tracer.instant(
                        "perf_regression", step_ms=round(elapsed * 1e3, 3))
                    # the same postmortem path as poison/rollback: the
                    # ring (with the perf_bad verdict trail) + the
                    # goodput partition at the moment of the trip
                    self.recorder.dump(
                        self._flight_record_dir(), "perf-regression",
                        extra={"step": state.iteration,
                               "trip": self.sentinel.trips,
                               "step_ms": round(elapsed * 1e3, 3),
                               "threshold_ms": round(
                                   self.sentinel.last_threshold, 3),
                               "goodput": self.ledger.snapshot()})

            if state.iteration % tcfg.log_interval == 0:
                self._training_log(state, stats, elapsed)
            self._tb_log(state, stats, elapsed)

            if (
                tcfg.eval_interval
                and self.valid_data_iterator is not None
                and state.iteration % tcfg.eval_interval == 0
            ):
                val = self.evaluate(state)
                ppl = float(np.exp(min(20.0, val)))
                print(f"validation loss at iteration {state.iteration}: "
                      f"{val:.6E} | ppl: {ppl:.4f}", flush=True)
                if (self._tb_writer is not None
                        and tcfg.log_validation_ppl_to_tensorboard):
                    # ref: --log_validation_ppl_to_tensorboard
                    # (training.py:833-839)
                    self._tb_writer.add_scalar("lm-loss-validation", val,
                                               state.iteration)
                    self._tb_writer.add_scalar("lm-loss-validation-ppl", ppl,
                                               state.iteration)
                    if hasattr(self._tb_writer, "flush"):
                        self._tb_writer.flush()

            if tcfg.save_interval and state.iteration % tcfg.save_interval == 0:
                self._save(state)

            # exit conditions (ref: training.py:712-748). Signal/duration
            # decisions are a CONSENSUS across hosts (allgather-MAX, ref:
            # dist_signal_handler.py:53-57, training.py:727-739) so a pod
            # where one host catches SIGTERM or crosses the limit first
            # exits together.
            from megatron_llm_tpu.parallel.multihost import (
                all_hosts_any,
                host_barrier,
            )

            if self.signal_handler is not None:
                if all_hosts_any(self.signal_handler.signals_received()):
                    # preemption fast-save: the all_hosts_any above is
                    # the BEFORE consensus (every host enters the save
                    # branch together); the barrier after the committed
                    # save keeps any host from tearing down its runtime
                    # while a peer is still writing shards — the pod
                    # exits as one.
                    print("exiting on termination signal — emergency "
                          "save", flush=True)
                    self.recorder.record("sigterm", step=state.iteration)
                    self._save(state, blocking=True)
                    # postmortem artifact AFTER the committed save (the
                    # save dir now exists even on a first-interval
                    # kill): the killed run's last-N-steps record,
                    # correlated to the emergency-saved iteration
                    self.recorder.dump(
                        self._flight_record_dir(), "sigterm",
                        extra={"step": state.iteration,
                               "consumed_train_samples":
                                   state.consumed_train_samples,
                               "goodput": self.ledger.snapshot(),
                               **({"costs": self.costs.snapshot()}
                                  if self.costs is not None else {})})
                    host_barrier("emergency-save-done")
                    break
            if tcfg.exit_duration_in_mins is not None:
                over = (time.time() - start_time) / 60.0 \
                    > tcfg.exit_duration_in_mins
                if all_hosts_any(over):
                    print("exiting on duration limit", flush=True)
                    self._save(state, blocking=True)
                    host_barrier("duration-save-done")
                    break
            if self._autoresume is not None and \
                    self._autoresume.termination_requested(state.iteration):
                print("exiting on autoresume termination request",
                      flush=True)
                self._save(state, blocking=True)
                host_barrier("autoresume-save-done")
                break
            if tcfg.exit_interval and state.iteration % tcfg.exit_interval == 0:
                print(f"exiting at iteration {state.iteration}", flush=True)
                break
        if self._trace_active:
            # early exit inside the profile window: flush the trace
            jax.profiler.stop_trace()
            self._trace_active = False
        # the one place the loop pays a full commit wait: exit. An
        # in-flight interval save must land before the process may die.
        if self._ckpt_manager is not None:
            self._ckpt_manager.wait_until_finished()
        if self.tcfg.trace_dir:
            path = self.tracer.export(os.path.join(
                self.tcfg.trace_dir, f"trace_train_{os.getpid()}.json"))
            if path:
                print(f"span trace exported to {path} "
                      f"(Perfetto / chrome://tracing)", flush=True)
        return state


def pretrain(
    model,
    tcfg: TrainConfig,
    pcfg: ParallelConfig,
    train_valid_test_dataset_provider: Callable,
    eod_token: Optional[int] = None,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
) -> TrainState:
    """One-call training entry (ref: pretrain training.py:54-196).

    `train_valid_test_dataset_provider(train_val_test_num_samples)` returns
    (train_ds, valid_ds, test_ds) with __len__/__getitem__->{'text'}.
    """
    from megatron_llm_tpu.data.data_samplers import build_pretraining_data_loader

    if tcfg.train_samples is not None:
        # sample-based duration (ref: --train_samples): the train split's
        # budget is exact; the iteration count (for eval cadence sizing)
        # accounts for batch-size rampup
        from megatron_llm_tpu.training.microbatches import (
            iterations_for_samples,
        )

        train_iters = iterations_for_samples(
            tcfg.train_samples, tcfg.global_batch_size,
            tcfg.micro_batch_size, pcfg.data_parallel_size,
            tcfg.rampup_batch_size,
        )
        train_budget = tcfg.train_samples
    else:
        train_iters = tcfg.train_iters or 0
        train_budget = train_iters * tcfg.global_batch_size
    eval_iters = (train_iters // max(tcfg.eval_interval, 1) + 1) * tcfg.eval_iters
    num_samples = [
        train_budget,
        eval_iters * tcfg.global_batch_size,
        tcfg.eval_iters * tcfg.global_batch_size,
    ]
    train_ds, valid_ds, test_ds = train_valid_test_dataset_provider(num_samples)

    trainer = Trainer(
        model, tcfg, pcfg, eod_token=eod_token,
        reset_position_ids=reset_position_ids,
        reset_attention_mask=reset_attention_mask,
        eod_mask_loss=eod_mask_loss,
    )
    state = trainer.setup()

    # multi-host: each process loads only its data-axis rows of every
    # global microbatch (parallel/multihost.py)
    row_range = None
    if trainer.ctx is not None and jax.process_count() > 1:
        from megatron_llm_tpu.parallel.multihost import process_row_range

        row_range = process_row_range(
            trainer.ctx, tcfg.micro_batch_size * pcfg.data_parallel_size
        )

    # the trainer's calculator is the single source of the current batch
    # size; the loader consults it live so --rampup_batch_size ramps
    # (ref: training.py:403 re-reads get_num_microbatches() every step)
    trainer.train_data_iterator = build_pretraining_data_loader(
        train_ds, state.consumed_train_samples, tcfg.micro_batch_size,
        pcfg.data_parallel_size, trainer.num_microbatches_calc.get,
        row_range=row_range,
    )
    trainer.valid_data_iterator = build_pretraining_data_loader(
        valid_ds, 0, tcfg.micro_batch_size, pcfg.data_parallel_size, 1,
        row_range=row_range,
    )

    state = trainer.train(state)
    if tcfg.save:
        trainer._save(state, blocking=True)
    return state
