"""Microbatch calculators (ref: megatron/microbatches.py).

`ConstantNumMicroBatches` (:59) and the linear global-batch-size ramp
`RampupBatchsizeNumMicroBatches` (:79-160): global batch grows from
`start` by `increment` every `ramp_samples` consumed samples.
"""

from __future__ import annotations

from typing import Optional, Sequence


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches: int = 1
        self.current_global_batch_size: int = 1

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples: int, consistency_check: bool = True):
        pass


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """ref: microbatches.py:59-78."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        micro_times_dp = micro_batch_size * data_parallel_size
        assert global_batch_size % micro_times_dp == 0, (
            f"global batch {global_batch_size} not divisible by "
            f"micro_batch*dp {micro_times_dp}"
        )
        self.num_micro_batches = global_batch_size // micro_times_dp
        assert self.num_micro_batches >= 1
        self.current_global_batch_size = global_batch_size


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """ref: microbatches.py:79-160 — batch ramps `start -> global` in
    `increment` steps spread over `ramp_samples` consumed samples."""

    def __init__(
        self,
        start_batch_size: int,
        batch_size_increment: int,
        ramp_samples: int,
        global_batch_size: int,
        micro_batch_size: int,
        data_parallel_size: int,
    ):
        super().__init__()
        assert global_batch_size > 0 and start_batch_size > 0
        assert batch_size_increment > 0 and ramp_samples >= 0
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel = micro_batch_size * data_parallel_size
        assert start_batch_size % self.micro_batch_times_data_parallel == 0
        assert batch_size_increment % self.micro_batch_times_data_parallel == 0
        assert global_batch_size % self.micro_batch_times_data_parallel == 0
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.global_batch_size = global_batch_size
        diff = global_batch_size - start_batch_size
        assert diff >= 0 and diff % batch_size_increment == 0
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = ramp_samples / max(num_increments, 1)
        self.update(0, consistency_check=False)

    def update(self, consumed_samples: int, consistency_check: bool = True):
        steps = int(consumed_samples / self.rampup_samples_per_increment)
        self.current_global_batch_size = min(
            self.start_batch_size + steps * self.batch_size_increment,
            self.global_batch_size,
        )
        if consistency_check:
            assert (
                self.current_global_batch_size
                % self.micro_batch_times_data_parallel
                == 0
            )
        self.num_micro_batches = (
            self.current_global_batch_size // self.micro_batch_times_data_parallel
        )


def iterations_for_samples(
    target_samples: int,
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
    rampup_batch_size: Optional[Sequence[int]] = None,
) -> int:
    """Exact iteration count to consume `target_samples` under the (possibly
    ramping) batch schedule — what the reference computes by stepping
    update_num_microbatches over train_samples (training.py:126-141).
    Walks the ramp phase step by step, then closes arithmetically."""
    calc = build_num_microbatches_calculator(
        global_batch_size, micro_batch_size, data_parallel_size,
        rampup_batch_size,
    )
    consumed, iters = 0, 0
    while consumed < target_samples:
        bs = calc.get_current_global_batch_size()
        if bs >= global_batch_size:  # ramp done (or constant): close out
            remaining = target_samples - consumed
            return iters + -(-remaining // bs)
        consumed += bs
        iters += 1
        calc.update(consumed, consistency_check=False)
    return iters


def build_num_microbatches_calculator(
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
    rampup_batch_size: Optional[Sequence[int]] = None,
) -> NumMicroBatchesCalculator:
    """ref: build_num_microbatches_calculator (microbatches.py:14-56)."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    assert len(rampup_batch_size) == 3
    return RampupBatchsizeNumMicroBatches(
        int(rampup_batch_size[0]), int(rampup_batch_size[1]),
        int(rampup_batch_size[2]), global_batch_size, micro_batch_size,
        data_parallel_size,
    )
