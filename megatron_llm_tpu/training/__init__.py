from megatron_llm_tpu.training.train_step import make_train_step  # noqa: F401
