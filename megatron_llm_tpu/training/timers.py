"""Named wall-clock timers (ref: megatron/timers.py:54-307).

Same interface shape: `timers('name', log_level).start()/.stop()`,
`timers.log(names)`, `timers.write(names, writer, iteration)`. On TPU,
device work is async — a timer that should include device time must be
stopped after a host sync (the trainer fetches the loss, which serves as
the barrier the reference gets from `torch.cuda.synchronize`).
"""

from __future__ import annotations

import time
from typing import List, Optional


class _Timer:
    def __init__(self, name: str, tracer=None):
        self.name = name
        self._elapsed = 0.0
        self._started = False
        self._start_time = 0.0
        # span-tracer ride-along (ISSUE 13): every start/stop interval
        # of a named timer also lands on the Perfetto timeline, so the
        # existing instrumentation points (train-step, batch-generator,
        # save-checkpoint, ...) need no second set of emit sites
        self._tracer = tracer

    def start(self):
        assert not self._started, f"timer {self.name} already started"
        self._start_time = time.perf_counter()
        self._started = True

    def stop(self):
        assert self._started, f"timer {self.name} not started"
        now = time.perf_counter()
        self._elapsed += now - self._start_time
        self._started = False
        if self._tracer is not None:
            self._tracer.complete(self.name, self._start_time, now)

    def reset(self):
        self._elapsed = 0.0
        self._started = False

    def elapsed(self, reset: bool = True) -> float:
        started = self._started
        if started:
            # the internal stop/start pair is bookkeeping, not a real
            # interval — it must not emit a trace span
            tracer, self._tracer = self._tracer, None
            self.stop()
            self._tracer = tracer
        total = self._elapsed
        if reset:
            self.reset()
        if started:
            self.start()
        return total


class Timers:
    """ref: Timers (timers.py:120-307); log_option max/minmax/all collapse
    to the single-process value in the single-controller runtime."""

    def __init__(self, log_level: int = 0, log_option: str = "minmax",
                 tracer=None):
        self._log_level = log_level
        self._log_option = log_option
        # optional telemetry.SpanTracer: timer intervals double as
        # trace spans (the trainer passes its tracer; None = no spans)
        self._tracer = tracer
        self._timers: dict = {}
        self._log_levels: dict = {}
        # one-shot run facts (remat policy, compiled temp/args bytes, ...)
        # recorded once and carried alongside the timers so a perf
        # trajectory is attributable to the configuration that produced it
        self._gauges: dict = {}
        self._gauges_unprinted: set = set()
        self._gauges_unwritten: set = set()

    def gauge(self, name: str, value):
        """Record a one-shot named value (number or string). Surfaced ONCE
        per channel: printed by the next `log()` and written by the next
        `write()` after being set (re-setting re-arms both)."""
        self._gauges[name] = value
        self._gauges_unprinted.add(name)
        self._gauges_unwritten.add(name)

    def gauges(self) -> dict:
        return dict(self._gauges)

    def __call__(self, name: str, log_level: Optional[int] = None) -> _Timer:
        if name not in self._timers:
            self._timers[name] = _Timer(name, tracer=self._tracer)
            self._log_levels[name] = log_level if log_level is not None else 0
        return self._timers[name]

    def log(
        self,
        names: Optional[List[str]] = None,
        normalizer: float = 1.0,
        reset: bool = True,
    ) -> Optional[str]:
        names = names if names is not None else list(self._timers)
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name not in self._timers:
                continue
            if self._log_levels[name] > self._log_level:
                continue
            t = self._timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            parts.append(f"{name}: {t:.2f}")
        if self._gauges_unprinted:
            gparts = [f"{n}: {self._gauges[n]}"
                      for n in self._gauges if n in self._gauges_unprinted]
            self._gauges_unprinted.clear()
            print("run facts | " + " | ".join(gparts), flush=True)
        if not parts:
            return None
        line = "time (ms) | " + " | ".join(parts)
        print(line, flush=True)
        return line

    def write(self, names: List[str], writer, iteration: int,
              normalizer: float = 1.0, reset: bool = False):
        """ref: Timers.write (timers.py:280-300) — tensorboard dump.
        Gauges not yet written ride along once (numeric via add_scalar,
        strings — e.g. the remat policy — via add_text when supported)."""
        for name in names:
            if name in self._timers:
                value = self._timers[name].elapsed(reset=reset) / normalizer
                writer.add_scalar(f"{name}-time", value, iteration)
        for name in [n for n in self._gauges if n in self._gauges_unwritten]:
            value = self._gauges[name]
            if isinstance(value, (int, float)):
                writer.add_scalar(name, value, iteration)
            elif hasattr(writer, "add_text"):
                writer.add_text(name, str(value), iteration)
            # consumed either way: a writer with no text sink will never
            # grow one, so retrying a string gauge forever is pointless
            self._gauges_unwritten.discard(name)
