"""The jitted training step.

Parity target: ref training.py:391-450 `train_step` — zero grad buffers,
microbatched fwd/bwd (the no-pipelining schedule,
ref: schedules.py:213-250), grad reduction across DP, clip + Adam, LR step.
On TPU the whole thing is ONE jitted, GSPMD-sharded function:

- gradient accumulation over microbatches is a `lax.scan` (no Python loop,
  no per-microbatch dispatch);
- the DP grad allreduce (ref: distributed.py:202-230) is emitted by XLA
  from the batch-dim sharding of the loss mean;
- the TP/SP collectives come from the parameter/activation shardings;
- the distributed-optimizer reduce-scatter/all-gather
  (ref: distrib_optimizer.py:522-610) comes from optimizer-state sharding.

Loss averaging over microbatches matches ref training.py:442-448.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.analysis.contracts import compile_contract
from megatron_llm_tpu.config import ModelConfig, ParallelConfig, TrainConfig
from megatron_llm_tpu.optimizer.optimizer import OptimizerState, optimizer_step


@compile_contract(
    "train.step",
    max_variants=12,  # num_microbatches buckets per trainer; the trainer
    # passes contract_key=num_microbatches so a microbatch-schedule
    # change that re-traces per step fails loudly at mint time. Raised
    # 8 -> 12 with the ZeRO-1 audit specializations (dp2 replicated /
    # zero1 / zero1-quantized, dp2tp2 zero1) minting in the global
    # bucket alongside the original tp2/dp2tp2 pair.
    collectives={
        "single": frozenset(),
        # pinned on the audit reference config (analysis/audit.py):
        # the TP activation/logit reductions lower to all-reduce, the
        # GSPMD param/embedding gathers to all-gather; dp grad
        # reduction folds into the same all-reduce family.
        "tp2": frozenset({"all-reduce", "all-gather"}),
        "dp2tp2": frozenset({"all-reduce", "all-gather"}),
        # pure-dp replicated adam: the dp grad reduction + scalar
        # reductions are the only collectives
        "dp2": frozenset({"all-reduce"}),
        # telemetry-on specialization (ISSUE 13): by contract IDENTICAL
        # to dp2 — span/recorder emission is host bookkeeping outside
        # the jit, so the lowered artifact may not change by one op.
        # The audit lowers this row with a live tracer+recorder around
        # the mint and _check_telemetry_parity pins inventory equality
        # + zero host callbacks vs the telemetry-off dp2 row.
        "dp2+telemetry": frozenset({"all-reduce"}),
        # cost-registry-on specialization (ISSUE 15): same contract as
        # +telemetry — mint-time cost capture READS the artifact
        # (lower + cost/memory analysis) and may not change one op;
        # the audit additionally pins compiled-FLOPs equality vs dp2.
        "dp2+costs": frozenset({"all-reduce"}),
        # ZeRO-1 explicit decomposition (optimizer/zero1.py): the ISSUE
        # 10 contract — per-bucket reduce-scatter of grads, all-gather
        # of updated params, all-reduce for loss/denominator/grad-norm
        # scalars and the replicated residue leaves
        "dp2+zero1": frozenset(
            {"all-reduce", "all-gather", "reduce-scatter"}),
        # quantized grad reduction: the bucket exchange is an int8
        # all-to-all (+ fp32 scales) instead of a reduce-scatter
        "dp2+zero1-quant": frozenset(
            {"all-reduce", "all-gather", "all-to-all"}),
        # overlap scheduling (ISSUE 12): the SAME collective inventory
        # as the eager rows — the backward-interleaved reduce-scatter
        # and the explicit per-bucket param all-gather reorder the
        # schedule, they add no collective kind. The interleaving
        # itself is pinned structurally by the audit's overlap report
        # (analysis/overlap.py): reduce-scatters between the per-group
        # backward loops, not after them.
        "dp2+zero1+overlap": frozenset(
            {"all-reduce", "all-gather", "reduce-scatter"}),
        "dp2+zero1-quant+overlap": frozenset(
            {"all-reduce", "all-gather", "all-to-all"}),
        # mixed-mesh zero1 keeps the GSPMD-spec path: no explicit
        # reduce-scatter op on this CPU pipeline (TPU's SPMD partitioner
        # forms one from the steered all-reduce+slice; not witnessable
        # in the CPU audit — GUIDE.md). The constrained grads/update DO
        # lower to real resharding collectives here: all-to-all and
        # collective-permute move the dp-sharded update shards, the
        # all-gather reassembles params — pinned at the audit config.
        "dp2tp2+zero1": frozenset(
            {"all-reduce", "all-gather", "all-to-all",
             "collective-permute"}),
    },
    tmp_bytes_budget=4 << 20,  # raised 2 -> 4 MiB with the ISSUE 12
    # overlap audit rows: they lower a DEEPER (4-layer, 2-microbatch)
    # reference specialization so the interleave pin has group
    # boundaries to witness — measured 3.6 MiB vs the 2-layer rows'
    # 1.8 MiB; the budget still pins relative regressions at the new
    # config set
    notes="the one fused fwd+bwd+optimizer step; audited on tp2/dp2/"
          "dp2x2 CPU meshes at the tiny reference config, zero1 "
          "(explicit + GSPMD-spec + quantized + overlap-scheduled) "
          "specializations included")
def make_train_step(model, tcfg: TrainConfig, pcfg: ParallelConfig,
                    batch_builder=None):
    """Returns train_step(params, opt_state, batch, lr, wd, rng,
    spike_threshold). `batch_builder` is the trainer's raw-batch
    adapter when one is installed — its presence excludes the explicit
    ZeRO-1 path (the builder's batch leaves/kwargs are not the GPT
    loss_terms surface the shard_map body splats).

    `batch` dict of (num_microbatches, batch, seq) arrays with keys
    tokens / labels / loss_mask (loss_mask optional). When
    num_microbatches == 1 a leading axis of 1 is still expected — keeps one
    trace for both cases.

    fp16 runs scale the loss before backward, unscale the accumulated
    grads, skip the step on overflow, and update the dynamic scale — the
    whole Float16OptimizerWithFloat16Params protocol
    (ref: optimizer/optimizer.py:270-466) inside the one jitted step.

    `spike_threshold` (optional TRACED fp32 scalar, the loss watchdog's
    current median + k*sigma, training/watchdog.py): when given, a step
    whose mean loss is non-finite or above it is SKIPPED in-step —
    params/optimizer untouched, stats["skipped"] set — by riding the
    same found_inf machinery the fp16 scaler uses, so bf16 runs get the
    identical no-host-round-trip skip path. Pass +inf for "no spike
    gating, still skip NaN/inf losses".

    ZeRO-1 (`pcfg.use_distributed_optimizer`, ISSUE 10): on pure-dp
    meshes with a loss_terms model (the GPT family) the gradient
    reduction is the EXPLICIT decomposition (optimizer/zero1.py):
    per-bucket reduce-scatter per microbatch into a dp-sharded fp32
    accumulator (opt-in int8-quantized wire via
    `pcfg.quantized_grad_reduce`), shard-local Adam on the dp-sharded
    m/v, then an all-gather of the updated params — bitwise-identical
    to the replicated path when quantization is off (tests/
    test_zero1.py). On mixed meshes (tp/cp > 1) the GSPMD-spec path
    steers the same layout with sharding constraints (all-reduce +
    slice on CPU; TPU forms reduce-scatter from the pattern).
    """
    from megatron_llm_tpu.optimizer.optimizer import get_grad_scaler
    from megatron_llm_tpu.optimizer.zero1 import (
        build_overlap_plan,
        build_zero1_plan,
        explicit_zero1_supported,
        make_explicit_param_gather,
        make_zero1_grad_fn,
    )
    from megatron_llm_tpu.parallel.mesh import get_context

    num_micro = pcfg.num_microbatches
    scaler = get_grad_scaler(tcfg)
    ctx = get_context()
    use_explicit = explicit_zero1_supported(model, pcfg, ctx,
                                            batch_builder=batch_builder)
    if (pcfg.quantized_grad_reduce or pcfg.overlap_grad_reduce
            or pcfg.overlap_param_gather) and not use_explicit:
        # the mesh-SHAPE combinations are rejected at config
        # construction; what remains here: a model without loss_terms
        # (BERT/T5/biencoder), an installed batch_builder, or a
        # missing/mismatched mesh context — falling back would silently
        # train full-precision under a flag that promises int8
        blocker = (
            "no mesh context installed" if ctx is None
            else f"mesh dp={ctx.dp} != configured "
                 f"dp={pcfg.data_parallel_size}"
            if ctx.dp != pcfg.data_parallel_size
            else "a batch_builder is installed (its batch is not the "
                 "loss_terms surface)" if batch_builder is not None
            else f"{type(model).__name__} exposes no loss_terms "
                 f"(GPT-family models do)")
        flags = ", ".join(
            f for f in ("quantized_grad_reduce", "overlap_grad_reduce",
                        "overlap_param_gather")
            if getattr(pcfg, f))
        raise ValueError(
            f"{flags} require(s) the explicit ZeRO-1 path, "
            f"which this run cannot take: {blocker}. Drop the flag or "
            "remove the blocker (docs/GUIDE.md, 'ZeRO-1 distributed "
            "optimizer')")
    zero1_gspmd = (
        not use_explicit
        and ctx is not None
        and pcfg.use_distributed_optimizer
        and pcfg.data_parallel_size > 1
        and pcfg.pipeline_parallel_size == 1
    )

    def loss_on_micro(params, micro, rng, loss_scale):
        # the batch dict's keys ARE the model-loss kwargs: GPT batches
        # carry tokens/labels/loss_mask/position_ids/attention_mask, BERT
        # adds tokentype_ids/sop_labels, T5 uses encoder/decoder fields —
        # one train step serves every model family.
        loss = model.loss(
            params,
            dropout_rng=rng,
            deterministic=rng is None,
            **micro,
        )
        if loss_scale is not None:
            # ref: MegatronOptimizer.scale_loss optimizer.py:116-120
            return loss * loss_scale, loss
        return loss, loss

    def _zero1_constrain(tree, params):
        """Mixed-mesh GSPMD-spec steering: pin each grad leaf to its
        zero1 spec so the m/v update runs shard-wise (the slice happens
        at the reduction, not after a full materialization)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from megatron_llm_tpu.parallel.sharding import (
            param_specs,
            zero1_spec,
        )

        specs = param_specs(model.cfg, params)
        flat_t, treedef = jax.tree.flatten(tree)
        flat_s, _ = jax.tree.flatten(
            specs, is_leaf=lambda x: isinstance(x, P))
        out = [
            jax.lax.with_sharding_constraint(
                t, NamedSharding(
                    ctx.mesh, zero1_spec(s, t.shape,
                                         pcfg.data_parallel_size)))
            for t, s in zip(flat_t, flat_s)
        ]
        return jax.tree.unflatten(treedef, out)

    def _gather_params(new_params, params):
        """The all-gather leg of the decomposition: updated params back
        to their dp-replicated (tp/pp-sharded) serving layout."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from megatron_llm_tpu.parallel.sharding import param_specs

        specs = param_specs(model.cfg, params)
        flat_p, treedef = jax.tree.flatten(new_params)
        flat_s, _ = jax.tree.flatten(
            specs, is_leaf=lambda x: isinstance(x, P))
        return jax.tree.unflatten(treedef, [
            jax.lax.with_sharding_constraint(t, NamedSharding(ctx.mesh, s))
            for t, s in zip(flat_p, flat_s)
        ])

    def train_step(params, opt_state: OptimizerState, batch, lr, wd,
                   rng=None, spike_threshold=None):
        loss_scale = (
            scaler.scale(opt_state.scaler) if scaler is not None else None
        )
        if use_explicit:
            # --overlap_grad_reduce picks the scheduled plan (layer-
            # group issue points threaded through the backward); the
            # eager Zero1Plan stays the bitwise oracle (ISSUE 12)
            if pcfg.overlap_grad_reduce:
                plan = build_overlap_plan(
                    model.cfg, params, pcfg.data_parallel_size,
                    bucket_mb=pcfg.grad_rs_bucket_mb)
            else:
                plan = build_zero1_plan(
                    model.cfg, params, pcfg.data_parallel_size,
                    bucket_mb=pcfg.grad_rs_bucket_mb)
            zgrad = make_zero1_grad_fn(
                model, ctx, plan, num_micro,
                quantized=pcfg.quantized_grad_reduce)
            grads, loss = zgrad(params, batch, rng, loss_scale)
        elif num_micro == 1:
            grad_fn = jax.value_and_grad(loss_on_micro, has_aux=True)
            micro = jax.tree.map(lambda x: x[0], batch)
            (_, loss), grads = grad_fn(params, micro, rng, loss_scale)
        else:
            grad_fn = jax.value_and_grad(loss_on_micro, has_aux=True)
            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )

            def body(carry, xs):
                acc_g, acc_l = carry
                micro, idx = xs
                mrng = jax.random.fold_in(rng, idx) if rng is not None else None
                (_, l), g = grad_fn(params, micro, mrng, loss_scale)
                acc_g = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), acc_g, g
                )
                return (acc_g, acc_l + l), None

            (grads, loss), _ = jax.lax.scan(
                body,
                (zero_grads, jnp.float32(0.0)),
                (batch, jnp.arange(num_micro)),
            )
            grads = jax.tree.map(lambda g: g / num_micro, grads)
            loss = loss / num_micro

        if zero1_gspmd:
            grads = _zero1_constrain(grads, params)

        if scaler is not None:
            # unscale; the overflow check rides optimizer_step's grad norm
            inv = 1.0 / loss_scale
            grads = jax.tree.map(lambda g: g * inv, grads)

        found_inf = None
        if spike_threshold is not None:
            # loss-level gate: NaN/inf losses AND watchdog spikes skip
            # the update exactly like an fp16 overflow skips it (the
            # grad-norm finiteness check inside optimizer_step still
            # applies on top). The fp16 loss SCALE only reacts to
            # genuine overflow, never to this gate — optimizer_step
            # keeps the two signals separate.
            found_inf = ~jnp.isfinite(loss) | (loss > spike_threshold)
        new_params, new_state, stats = optimizer_step(
            params, grads, opt_state, tcfg, lr, weight_decay=wd,
            found_inf=found_inf, scaler=scaler,
        )
        if use_explicit or zero1_gspmd:
            # the all-gather leg: each dp rank computed only its shard
            # of the update (grads + m/v arrive dp-sharded, so GSPMD
            # keeps the elementwise Adam shard-wise); this constraint
            # reassembles the dp-replicated params for the next forward
            if use_explicit and pcfg.overlap_param_gather:
                # explicit per-bucket all-gathers, first-needed-first
                # and double-buffered (ISSUE 12); the constraint after
                # is a no-op re-stamp of the param_specs shardings
                new_params = make_explicit_param_gather(ctx, plan)(
                    new_params)
            new_params = _gather_params(new_params, params)
        stats["loss"] = loss
        return new_params, new_state, stats

    return train_step


@compile_contract(
    "train.eval_step",
    max_variants=4,  # one per eval flavor a trainer can build: plain,
    # pipelined (pp_eval), batch-builder (generic_eval) — the trainer
    # records those variants under the same contract at their jit sites
    collectives=None,  # pp lowering needs a stage-sharded model; the
    # pipeline suites exercise it — variants/markers still audited
    notes="eval is interval-gated, not per-step; the contract exists "
          "so the jit sites are registry-visible (GR007)")
def make_eval_step(model):
    """ref: evaluate (training.py:754-810) inner step."""

    def eval_step(params, batch):
        loss = model.loss(
            params,
            batch["tokens"],
            batch["labels"],
            loss_mask=batch.get("loss_mask"),
            deterministic=True,
        )
        return loss

    return eval_step
