"""The jitted training step.

Parity target: ref training.py:391-450 `train_step` — zero grad buffers,
microbatched fwd/bwd (the no-pipelining schedule,
ref: schedules.py:213-250), grad reduction across DP, clip + Adam, LR step.
On TPU the whole thing is ONE jitted, GSPMD-sharded function:

- gradient accumulation over microbatches is a `lax.scan` (no Python loop,
  no per-microbatch dispatch);
- the DP grad allreduce (ref: distributed.py:202-230) is emitted by XLA
  from the batch-dim sharding of the loss mean;
- the TP/SP collectives come from the parameter/activation shardings;
- the distributed-optimizer reduce-scatter/all-gather
  (ref: distrib_optimizer.py:522-610) comes from optimizer-state sharding.

Loss averaging over microbatches matches ref training.py:442-448.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.analysis.contracts import compile_contract
from megatron_llm_tpu.config import ModelConfig, ParallelConfig, TrainConfig
from megatron_llm_tpu.optimizer.optimizer import OptimizerState, optimizer_step


@compile_contract(
    "train.step",
    max_variants=8,  # num_microbatches buckets per trainer; the trainer
    # passes contract_key=num_microbatches so a microbatch-schedule
    # change that re-traces per step fails loudly at mint time
    collectives={
        "single": frozenset(),
        # pinned on the audit reference config (analysis/audit.py):
        # the TP activation/logit reductions lower to all-reduce, the
        # GSPMD param/embedding gathers to all-gather; dp grad
        # reduction folds into the same all-reduce family. ZeRO-1
        # (ROADMAP item 2) is expected to ADD reduce-scatter here —
        # that PR updates this declaration with its justification.
        "tp2": frozenset({"all-reduce", "all-gather"}),
        "dp2tp2": frozenset({"all-reduce", "all-gather"}),
    },
    tmp_bytes_budget=2 << 20,
    notes="the one fused fwd+bwd+optimizer step; audited on tp2 and "
          "dp2x2 CPU meshes at the tiny reference config")
def make_train_step(model, tcfg: TrainConfig, pcfg: ParallelConfig):
    """Returns train_step(params, opt_state, batch, lr, wd, rng,
    spike_threshold).

    `batch` dict of (num_microbatches, batch, seq) arrays with keys
    tokens / labels / loss_mask (loss_mask optional). When
    num_microbatches == 1 a leading axis of 1 is still expected — keeps one
    trace for both cases.

    fp16 runs scale the loss before backward, unscale the accumulated
    grads, skip the step on overflow, and update the dynamic scale — the
    whole Float16OptimizerWithFloat16Params protocol
    (ref: optimizer/optimizer.py:270-466) inside the one jitted step.

    `spike_threshold` (optional TRACED fp32 scalar, the loss watchdog's
    current median + k*sigma, training/watchdog.py): when given, a step
    whose mean loss is non-finite or above it is SKIPPED in-step —
    params/optimizer untouched, stats["skipped"] set — by riding the
    same found_inf machinery the fp16 scaler uses, so bf16 runs get the
    identical no-host-round-trip skip path. Pass +inf for "no spike
    gating, still skip NaN/inf losses".
    """
    from megatron_llm_tpu.optimizer.optimizer import get_grad_scaler

    num_micro = pcfg.num_microbatches
    scaler = get_grad_scaler(tcfg)

    def loss_on_micro(params, micro, rng, loss_scale):
        # the batch dict's keys ARE the model-loss kwargs: GPT batches
        # carry tokens/labels/loss_mask/position_ids/attention_mask, BERT
        # adds tokentype_ids/sop_labels, T5 uses encoder/decoder fields —
        # one train step serves every model family.
        loss = model.loss(
            params,
            dropout_rng=rng,
            deterministic=rng is None,
            **micro,
        )
        if loss_scale is not None:
            # ref: MegatronOptimizer.scale_loss optimizer.py:116-120
            return loss * loss_scale, loss
        return loss, loss

    def train_step(params, opt_state: OptimizerState, batch, lr, wd,
                   rng=None, spike_threshold=None):
        loss_scale = (
            scaler.scale(opt_state.scaler) if scaler is not None else None
        )
        grad_fn = jax.value_and_grad(loss_on_micro, has_aux=True)

        if num_micro == 1:
            micro = jax.tree.map(lambda x: x[0], batch)
            (_, loss), grads = grad_fn(params, micro, rng, loss_scale)
        else:
            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )

            def body(carry, xs):
                acc_g, acc_l = carry
                micro, idx = xs
                mrng = jax.random.fold_in(rng, idx) if rng is not None else None
                (_, l), g = grad_fn(params, micro, mrng, loss_scale)
                acc_g = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), acc_g, g
                )
                return (acc_g, acc_l + l), None

            (grads, loss), _ = jax.lax.scan(
                body,
                (zero_grads, jnp.float32(0.0)),
                (batch, jnp.arange(num_micro)),
            )
            grads = jax.tree.map(lambda g: g / num_micro, grads)
            loss = loss / num_micro

        if scaler is not None:
            # unscale; the overflow check rides optimizer_step's grad norm
            inv = 1.0 / loss_scale
            grads = jax.tree.map(lambda g: g * inv, grads)

        found_inf = None
        if spike_threshold is not None:
            # loss-level gate: NaN/inf losses AND watchdog spikes skip
            # the update exactly like an fp16 overflow skips it (the
            # grad-norm finiteness check inside optimizer_step still
            # applies on top). The fp16 loss SCALE only reacts to
            # genuine overflow, never to this gate — optimizer_step
            # keeps the two signals separate.
            found_inf = ~jnp.isfinite(loss) | (loss > spike_threshold)
        new_params, new_state, stats = optimizer_step(
            params, grads, opt_state, tcfg, lr, weight_decay=wd,
            found_inf=found_inf, scaler=scaler,
        )
        stats["loss"] = loss
        return new_params, new_state, stats

    return train_step


@compile_contract(
    "train.eval_step",
    max_variants=4,  # one per eval flavor a trainer can build: plain,
    # pipelined (pp_eval), batch-builder (generic_eval) — the trainer
    # records those variants under the same contract at their jit sites
    collectives=None,  # pp lowering needs a stage-sharded model; the
    # pipeline suites exercise it — variants/markers still audited
    notes="eval is interval-gated, not per-step; the contract exists "
          "so the jit sites are registry-visible (GR007)")
def make_eval_step(model):
    """ref: evaluate (training.py:754-810) inner step."""

    def eval_step(params, batch):
        loss = model.loss(
            params,
            batch["tokens"],
            batch["labels"],
            loss_mask=batch.get("loss_mask"),
            deterministic=True,
        )
        return loss

    return eval_step
