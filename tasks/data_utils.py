"""Shared sample-building utilities for downstream tasks.

Parity target: ref tasks/data_utils.py — [CLS] A [SEP] B [SEP] assembly
with types/paddings, the A/B trim loop, and text cleaning.
"""

from __future__ import annotations

import numpy as np


def clean_text(text: str) -> str:
    """ref: clean_text (data_utils.py:99-107)."""
    text = text.replace("\n", " ").replace("\t", " ")
    for _ in range(3):
        text = text.replace("  ", " ")
    return text.strip()


def build_sample(ids, types, paddings, label, unique_id) -> dict:
    """ref: build_sample (data_utils.py:20-32)."""
    return {
        "text": np.array(ids, np.int64),
        "types": np.array(types, np.int64),
        "padding_mask": np.array(paddings, np.int64),
        "label": int(label),
        "uid": int(unique_id),
    }


def build_tokens_types_paddings_from_text(text_a, text_b, tokenizer,
                                          max_seq_length):
    """ref: data_utils.py:35-46."""
    a_ids = tokenizer.tokenize(text_a)
    b_ids = tokenizer.tokenize(text_b) if text_b is not None else None
    return build_tokens_types_paddings_from_ids(
        a_ids, b_ids, max_seq_length, tokenizer.cls, tokenizer.sep,
        tokenizer.pad,
    )


def build_tokens_types_paddings_from_ids(a_ids, b_ids, max_seq_length,
                                         cls_id, sep_id, pad_id):
    """ref: data_utils.py:49-97 — trim A (and tail-trim B) to fit, then
    [CLS] A [SEP] [B [SEP]] + padding."""
    a_ids = list(a_ids)
    b_ids = list(b_ids) if b_ids is not None else None
    # room for [CLS] A [SEP] (+ B [SEP])
    budget = max_seq_length - (3 if b_ids is not None else 2)
    if b_ids is None:
        a_ids = a_ids[:budget]
    else:
        while len(a_ids) + len(b_ids) > budget:
            if len(a_ids) > len(b_ids):
                a_ids.pop()
            else:
                b_ids.pop()

    ids = [cls_id] + a_ids + [sep_id]
    types = [0] * len(ids)
    if b_ids is not None:
        ids += b_ids + [sep_id]
        types += [1] * (len(b_ids) + 1)
    paddings = [1] * len(ids)
    n_pad = max_seq_length - len(ids)
    ids += [pad_id] * n_pad
    types += [pad_id] * n_pad
    paddings += [0] * n_pad
    return ids, types, paddings
