"""Open-retrieval QA validation: answer matching + top-k accuracy.

Parity target: ref tasks/orqa/unsupervised/qa_utils.py (DPR-derived
`calculate_matches`/`check_answer`/`has_answer`) and the DPR
SimpleTokenizer (tokenizers.py) it matches with. The TPU port keeps the
same matching semantics — unicode-normalized, lowercased word-token
subsequence containment (match_type "string") or regex search — without
the multiprocessing pool (the matching is string work; the heavy part,
retrieval, runs on device).
"""

from __future__ import annotations

import re
import unicodedata
from collections import namedtuple
from typing import Dict, List, Tuple

QAMatchStats = namedtuple("QAMatchStats", ["top_k_hits",
                                           "questions_doc_hits"])

# DPR SimpleTokenizer equivalent: alphanumeric runs or single
# non-space chars
_SIMPLE_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)


def _normalize(text: str) -> str:
    return unicodedata.normalize("NFD", text)


def tokenize_words(text: str, lower: bool = True) -> List[str]:
    """DPR SimpleTokenizer.words(uncased=True) equivalent."""
    toks = _SIMPLE_RE.findall(_normalize(text))
    return [t.lower() for t in toks] if lower else toks


def has_answer(answers: List[str], text: str,
               match_type: str = "string") -> bool:
    """ref: qa_utils.py has_answer — string: token-subsequence
    containment; regex: pattern search."""
    text = _normalize(text)
    if match_type == "string":
        text_tokens = tokenize_words(text)
        for answer in answers:
            answer_tokens = tokenize_words(_normalize(answer))
            n = len(answer_tokens)
            if n == 0:
                continue
            for i in range(0, len(text_tokens) - n + 1):
                if answer_tokens == text_tokens[i:i + n]:
                    return True
        return False
    if match_type == "regex":
        for answer in answers:
            try:
                pattern = re.compile(_normalize(answer),
                                     flags=re.IGNORECASE | re.UNICODE
                                     | re.MULTILINE)
            except re.error:
                continue
            if pattern.search(text) is not None:
                return True
        return False
    raise ValueError(match_type)


def check_answer(answers: List[str], doc_ids, all_docs,
                 match_type: str = "string") -> List[bool]:
    """Per retrieved doc: does it contain any gold answer
    (ref: qa_utils.py check_answer)."""
    hits = []
    for doc_id in doc_ids:
        doc = all_docs.get(doc_id)
        text = doc[0] if doc is not None else None
        hits.append(bool(text) and has_answer(answers, text, match_type))
    return hits


def calculate_matches(
    all_docs: Dict[object, Tuple[str, str]],
    answers: List[List[str]],
    closest_docs: List[Tuple[List[object], List[float]]],
    match_type: str = "string",
) -> QAMatchStats:
    """ref: qa_utils.py calculate_matches — top_k_hits[k] = number of
    questions whose answer appears in the top-(k+1) retrieved docs."""
    scores = [
        check_answer(ans, doc_ids, all_docs, match_type)
        for ans, (doc_ids, _) in zip(answers, closest_docs)
    ]
    n_docs = len(closest_docs[0][0])
    top_k_hits = [0] * n_docs
    for question_hits in scores:
        best = next((i for i, x in enumerate(question_hits) if x), None)
        if best is not None:
            top_k_hits[best:] = [v + 1 for v in top_k_hits[best:]]
    return QAMatchStats(top_k_hits, scores)
