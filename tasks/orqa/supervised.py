"""Supervised retriever finetuning on Natural Questions (RET-FINETUNE-NQ).

Parity target: ref tasks/orqa/supervised/{data.py,finetune.py,eval_utils.py}
— DPR-format json samples {question, answers, positive_ctxs[, hard
negatives]}, each batch trains the biencoder with in-batch softmax
retrieval (every other sample's positive context is a negative; one hard
negative per query optionally appended, ref finetune.py:96-150), and
validation reports in-batch top-k retrieval accuracy
(ref eval_utils.py:124-180).

TPU-first: the whole step (two tower forwards, the (b, b[*2]) score
matmul, CE, Adam) is one jitted function; the reference's cross-GPU
context gather (finetune.py:26-44) is GSPMD's job.
"""

from __future__ import annotations

import json
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def read_dpr_json(path: str) -> List[dict]:
    """DPR retriever-train format (ref: data.py process_samples_from_...).
    Accepts a json array or jsonl."""
    with open(path) as f:
        first = f.read(1)
        f.seek(0)
        if first == "[":
            return json.load(f)
        return [json.loads(ln) for ln in f if ln.strip()]


def _encode(tokenizer, text: str, title: Optional[str], max_len: int):
    ids = tokenizer.tokenize(text)
    if title is not None:
        ids = tokenizer.tokenize(title) + [tokenizer.sep] + ids
    ids = [tokenizer.cls] + ids[: max_len - 2] + [tokenizer.sep]
    out = np.full((max_len,), tokenizer.pad, np.int32)
    out[: len(ids)] = ids
    mask = np.zeros((max_len,), np.int32)
    mask[: len(ids)] = 1
    return out, mask


class OpenRetrievalDataset:
    """(query, positive ctx[, hard negative ctx]) token batches
    (ref: data.py OpenRetrievalAbstractDataset)."""

    def __init__(self, path: str, tokenizer, max_seq_length: int = 128,
                 use_hard_negatives: bool = False, seed: int = 1234):
        self.samples = read_dpr_json(path)
        self.tokenizer = tokenizer
        self.max_seq_length = max_seq_length
        self.use_hard_negatives = use_hard_negatives
        self.rng = np.random.RandomState(seed)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        s = self.samples[idx]
        q_ids, q_mask = _encode(self.tokenizer, s["question"], None,
                                self.max_seq_length)
        pos = s["positive_ctxs"][0]
        c_ids, c_mask = _encode(self.tokenizer, pos["text"],
                                pos.get("title"), self.max_seq_length)
        out = {"query": q_ids, "query_mask": q_mask,
               "context": c_ids, "context_mask": c_mask}
        if self.use_hard_negatives:
            negs = s.get("hard_negative_ctxs") or s.get("negative_ctxs") \
                or []
            if negs:
                neg = negs[int(self.rng.randint(len(negs)))]
                n_ids, n_mask = _encode(self.tokenizer, neg["text"],
                                        neg.get("title"),
                                        self.max_seq_length)
                out["neg_valid"] = np.int32(1)
            else:
                # no negatives for this sample: emit a PAD row the loss
                # masks out entirely — duplicating the positive would
                # split its softmax mass and cancel the gradient
                n_ids = np.full((self.max_seq_length,), self.tokenizer.pad,
                                np.int32)
                n_mask = np.zeros((self.max_seq_length,), np.int32)
                n_mask[0] = 1  # keep one live token for the encoder
                out["neg_valid"] = np.int32(0)
            out["neg_context"] = n_ids
            out["neg_context_mask"] = n_mask
        return out


def _batch(ds, idxs):
    rows = [ds[int(i)] for i in idxs]
    return {k: jnp.asarray(np.stack([r[k] for r in rows]))
            for k in rows[0]}


def _embed(model, tower, params, tokens, mask):
    """Shared/per-tower dispatch used by loss AND eval."""
    p = params["shared"] if "shared" in params else params[tower]
    return model.embed_text(p, tokens, mask)


def make_loss_fn(model, use_hard_negatives: bool):
    """In-batch softmax retrieval CE; hard negatives append b more
    context columns, pad rows masked out via neg_valid
    (ref: finetune.py:96-150)."""
    from megatron_llm_tpu.parallel.cross_entropy import cross_entropy

    def loss_fn(params, batch, rng=None):
        q = _embed(model, "query", params, batch["query"],
                   batch["query_mask"])
        c = _embed(model, "context", params, batch["context"],
                   batch["context_mask"])
        col_mask = None
        if use_hard_negatives and "neg_context" in batch:
            n = _embed(model, "context", params, batch["neg_context"],
                       batch["neg_context_mask"])
            c = jnp.concatenate([c, n], axis=0)  # (2b, d)
            col_mask = jnp.concatenate(
                [jnp.ones((q.shape[0],), jnp.float32),
                 batch["neg_valid"].astype(jnp.float32)]
            )
        scores = q.astype(jnp.float32) @ c.astype(jnp.float32).T
        if col_mask is not None:
            scores = jnp.where(col_mask[None, :] > 0, scores, NEG_INF)
        targets = jnp.arange(q.shape[0])
        losses = cross_entropy(scores, targets)
        top1 = jnp.mean(
            (jnp.argmax(scores, axis=-1) == targets).astype(jnp.float32)
        )
        return jnp.mean(losses), top1

    return loss_fn


def in_batch_topk_accuracy(model, params, ds, batch_size: int,
                           ks=(1, 5)) -> dict:
    """Validation: retrieval rank of each query's own positive within the
    batch (ref: eval_utils.py retrieval_loss + topk_accuracy)."""

    @jax.jit
    def score(params, batch):
        q = _embed(model, "query", params, batch["query"],
                   batch["query_mask"])
        c = _embed(model, "context", params, batch["context"],
                   batch["context_mask"])
        return q.astype(jnp.float32) @ c.astype(jnp.float32).T

    hits = {k: 0 for k in ks}
    total = 0
    for lo in range(0, len(ds) - batch_size + 1, batch_size):
        batch = _batch(ds, range(lo, lo + batch_size))
        s = np.asarray(score(params, batch))
        order = np.argsort(-s, axis=-1)
        for i in range(s.shape[0]):
            rank = int(np.where(order[i] == i)[0][0])
            for k in ks:
                hits[k] += rank < k
        total += s.shape[0]
    return {k: hits[k] / max(total, 1) for k in ks}


def finetune_retriever(model, params, train_ds, valid_ds=None,
                       epochs: int = 2, batch_size: int = 8,
                       lr: float = 2e-5, use_hard_negatives: bool = False,
                       seed: int = 1234, log_interval: int = 10):
    """Epoch loop (ref: finetune.py main via finetune_utils.finetune)."""
    import optax

    loss_fn = make_loss_fn(model, use_hard_negatives)
    opt = optax.adamw(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        (loss, top1), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, top1

    rng = np.random.RandomState(seed)
    it = 0
    for epoch in range(epochs):
        order = rng.permutation(len(train_ds))
        for lo in range(0, len(train_ds) - batch_size + 1, batch_size):
            batch = _batch(train_ds, order[lo:lo + batch_size])
            params, opt_state, loss, top1 = step(params, opt_state, batch)
            it += 1
            if it % log_interval == 0:
                print(f"epoch {epoch} iter {it}: loss "
                      f"{float(loss):.4f} in-batch top1 "
                      f"{float(top1):.3f}", flush=True)
        if valid_ds is not None:
            acc = in_batch_topk_accuracy(model, params, valid_ds,
                                         batch_size)
            print(f"epoch {epoch} validation in-batch accuracy: "
                  + ", ".join(f"top-{k} {v:.4f}"
                              for k, v in acc.items()),
                  flush=True)
    return params
