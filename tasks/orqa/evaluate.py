"""ORQA retriever evaluation: embed evidence + queries, MIPS, top-k hits.

Parity target: ref tasks/orqa/evaluate_orqa.py + evaluate_utils.py
(ORQAEvaluator) + megatron/indexer.py. The reference pipeline: an
IndexBuilder embeds every evidence block with the biencoder's context
tower into a FAISS index; queries embed with the query tower; FAISS MIPS
returns top-k; `calculate_matches` scores answer containment.

TPU-first design: maximum-inner-product search over a few million
d-dim embeddings IS a (Q, d) x (d, N) matmul + lax.top_k — exactly what
the MXU is for. The evidence matrix is embedded in jitted batches and the
search runs as a chunked device matmul with a running top-k merge; FAISS (approximate, CPU/GPU) is
deliberately not a dependency.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tasks.orqa.nq import read_nq_file, tokenize_queries
from tasks.orqa.qa_utils import calculate_matches


def read_evidence_tsv(path: str) -> List[Tuple[object, str, str]]:
    """The DPR/ref psgs_w100.tsv format: `id \\t text \\t title` with a
    header row (ref: megatron/data/orqa_wiki_dataset.py)."""
    import csv

    docs = []
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter="\t")
        for i, row in enumerate(reader):
            if i == 0 and row and row[0] == "id":
                continue
            if len(row) < 3:
                continue
            docs.append((row[0], row[1], row[2]))
    return docs


class ORQAEvaluator:
    """ref: evaluate_utils.py ORQAEvaluator."""

    def __init__(self, model, params, tokenizer, seq_length: int = 64,
                 batch_size: int = 32):
        self.model = model  # BiEncoderModel
        self.params = params
        self.tokenizer = tokenizer
        self.seq_length = seq_length
        self.batch_size = batch_size
        self._embed = jax.jit(
            lambda tower, toks, mask: model.embed_text(tower, toks, mask),
            static_argnums=(),
        )
        self.evidence_ids: Optional[list] = None
        self.evidence_emb: Optional[np.ndarray] = None
        self.all_docs: dict = {}

    def _tower(self, name):
        p = self.params
        return p["shared"] if "shared" in p else p[name]

    def _embed_texts(self, texts: List[str], tower: str) -> np.ndarray:
        out = []
        bs = self.batch_size
        for i in range(0, len(texts), bs):
            chunk = texts[i:i + bs]
            pad = bs - len(chunk)  # keep one compiled shape
            toks, mask, _ = tokenize_queries(
                self.tokenizer, chunk + [""] * pad, self.seq_length
            )
            emb = self._embed(self._tower(tower), jnp.asarray(toks),
                              jnp.asarray(mask))
            out.append(np.asarray(emb, np.float32)[: len(chunk)])
        return np.concatenate(out, axis=0)

    def build_index(self, docs: List[Tuple[object, str, str]]):
        """Embed evidence blocks with the CONTEXT tower (ref:
        megatron/indexer.py IndexBuilder.build_and_save_index). `docs` =
        [(doc_id, text, title)]."""
        self.evidence_ids = [d[0] for d in docs]
        self.all_docs = {d[0]: (d[1], d[2]) for d in docs}
        self.evidence_emb = self._embed_texts(
            [d[1] for d in docs], "context"
        )
        return self.evidence_emb

    def load_index(self, docs: List[Tuple[object, str, str]],
                   embedding_path: str):
        """Use a PREBUILT embedding store (tools/build_retrieval_index.py
        -> OpenRetrievalDataStore) instead of re-embedding the evidence —
        the ref realm_index load path (realm_index.py:50-60)."""
        from megatron_llm_tpu.data.realm_index import OpenRetrievalDataStore

        store = OpenRetrievalDataStore(embedding_path)
        if not store.embed_data:
            raise FileNotFoundError(
                f"no embedding store at {store.embedding_path} — build it "
                "with tools/build_retrieval_index.py"
            )
        self.evidence_ids = [d[0] for d in docs]
        self.all_docs = {d[0]: (d[1], d[2]) for d in docs}
        self.evidence_emb = np.stack(
            [store.embed_data[int(d[0])] for d in docs]
        ).astype(np.float32)
        return self.evidence_emb

    def retrieve(self, questions: List[str], topk: int = 20,
                 chunk_rows: int = 1 << 20):
        """MIPS: (Q, d) @ (d, N) + top-k (the FAISS replacement) via the
        shared chunked-search implementation
        (megatron_llm_tpu.data.realm_index.MIPSIndex — the score matrix
        never materializes; evidence streams through the device one
        <=chunk_rows slice at a time). Index rows are evidence-list
        POSITIONS, mapped back to evidence ids on return."""
        from megatron_llm_tpu.data.realm_index import MIPSIndex

        assert self.evidence_emb is not None, "call build_index first"
        index = MIPSIndex(self.evidence_emb.shape[1],
                          {i: e for i, e in enumerate(self.evidence_emb)},
                          chunk_rows=chunk_rows)
        q = self._embed_texts(questions, "query")
        scores, pos = index.search_mips_index(q, topk)
        return [
            ([self.evidence_ids[j] for j in pos[i]], list(scores[i]))
            for i in range(len(questions))
        ]

    def evaluate(self, qa_file: str, split: str = "DEV", topk: int = 20,
                 match_type: str = "string"):
        """ref: evaluate_utils.py ORQAEvaluator.evaluate — prints and
        returns the top-k hit rates."""
        data = read_nq_file(qa_file)
        questions = [q for q, _ in data]
        answers = [a for _, a in data]
        closest = self.retrieve(questions, topk)
        stats = calculate_matches(self.all_docs, answers, closest,
                                  match_type)
        n = len(questions)
        rates = [hits / n for hits in stats.top_k_hits]
        for k in (1, 5, 20, 100):
            if k <= len(rates):
                print(f"{split} top-{k} accuracy: {rates[k-1]:.4f}",
                      flush=True)
        return rates, stats
