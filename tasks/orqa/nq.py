"""Google Natural Questions open-retrieval eval data.

Parity target: ref tasks/orqa/unsupervised/nq.py — the NQ open TSV format
`question \\t ["answer", ...]` (answers as a python/json list literal),
tokenized to fixed-length query batches with [CLS]/[SEP] + pad masks.
"""

from __future__ import annotations

import ast
import csv
from typing import List, Tuple

import numpy as np


def read_nq_file(path: str) -> List[Tuple[str, List[str]]]:
    """[(question, [answers...])] (ref: nq.py NQDataset.process_samples)."""
    rows = []
    with open(path, newline="") as f:
        for row in csv.reader(f, delimiter="\t"):
            if len(row) < 2:  # blank or truncated line: skip
                continue
            question = row[0]
            try:
                answers = ast.literal_eval(row[1])
            except (ValueError, SyntaxError):
                answers = [row[1]]
            rows.append((question, [str(a) for a in answers]))
    return rows


def tokenize_queries(tokenizer, questions: List[str], max_len: int):
    """Fixed-length [CLS] q [SEP] batches -> (tokens, pad_mask, types)
    int32 arrays (ref: nq.py build_tokens_types_paddings)."""
    b = len(questions)
    tokens = np.full((b, max_len), tokenizer.pad, np.int32)
    mask = np.zeros((b, max_len), np.int32)
    types = np.zeros((b, max_len), np.int32)
    for i, q in enumerate(questions):
        ids = [tokenizer.cls] + tokenizer.tokenize(q)[: max_len - 2] \
            + [tokenizer.sep]
        tokens[i, : len(ids)] = ids
        mask[i, : len(ids)] = 1
    return tokens, mask, types
