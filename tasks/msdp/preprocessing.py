"""Wizard-of-Wikipedia preprocessing for multi-stage dialogue prompting.

Parity target: ref tasks/msdp/preprocessing.py. Implemented surface:

- `process_wow_dataset` (ref :42-127): WoW dialog json -> the 4-column
  test format `topic \\t context [SEP]-joined \\t knowledge \\t response`
  plus the knowledge/response reference files;
- `get_database` (ref :243-320): the filtered per-topic prompt-instance
  database from a processed train file;
- `prompt_selection_for_knowledge_generation` (ref :364-460): per test
  sample, pick the top-k most similar training instances for the topic and
  emit the jsonl prompt dict keyed `topic + " " + last_turn`. DEPARTURE:
  the reference ranks candidates with a torch DPR encoder checkpoint
  (--model_file); here similarity is normalized-token F1 overlap (no
  checkpoint dependency) unless an `encode_fn` is supplied (e.g. our
  biencoder's embed_text);
- `prompt_selection_for_response_generation` (ref :462-531): seeded
  random selection of response-generation prompt lines;
- `prepare_input_for_response_generation` (ref :533-559): merge generated
  knowledge back into the test file.

Tokenization for the response reference file uses nltk's word_tokenize
when available and a regex fallback otherwise.
"""

from __future__ import annotations

import json
import re

import numpy as np

from tasks.msdp.metrics import f1_score, normalize_answer


def word_tokenize(text: str):
    try:
        from nltk import word_tokenize as nltk_tok

        return nltk_tok(text)
    except Exception:
        return re.findall(r"\w+|[^\w\s]", text)


def process_wow_dataset(raw_file, processed_file, knwl_ref_file=None,
                        resp_ref_file=None):
    """ref: preprocessing.py:42-127."""
    with open(raw_file) as fr:
        dialog_data = json.load(fr)

    fproc = open(processed_file, "w")
    fknwl = open(knwl_ref_file, "w") if knwl_ref_file else None
    fresp = open(resp_ref_file, "w") if resp_ref_file else None
    try:
        for sample in dialog_data:
            turn_list = []
            for j, turn in enumerate(sample["dialog"]):
                text = turn["text"]
                if not text.endswith(("?", ".", "!")):
                    text = text + "."
                if j == 0:
                    turn_list.append(text)
                    continue
                speaker = turn["speaker"].lower()
                if "wizard" in speaker:
                    checked_sentence = list(
                        turn.get("checked_sentence", {}).values())
                    checked_passage = list(
                        turn.get("checked_passage", {}).values())
                    assert len(checked_sentence) <= 1
                    knowledge = (checked_sentence[0] if checked_sentence
                                 else "no_passages_used")
                    passage = (checked_passage[0]
                               if len(checked_passage) == 1
                               else "no_passages_used")
                    topic = (passage if passage != "no_passages_used"
                             else sample["chosen_topic"])
                    dialog_context = " [SEP] ".join(turn_list)
                    response = text
                    turn_list.append(response)
                    fproc.write(f"{topic}\t{dialog_context}\t{knowledge}"
                                f"\t{response}\n")
                    if fknwl:
                        fknwl.write(knowledge + "\n")
                    if fresp:
                        fresp.write(
                            " ".join(word_tokenize(response)) + "\n")
                else:
                    assert "apprentice" in speaker
                    turn_list.append(text)
    finally:
        fproc.close()
        if fknwl:
            fknwl.close()
        if fresp:
            fresp.close()


def get_database(test_datapath, train_datapath, data_type="wow_seen"):
    """ref: preprocessing.py:243-320 — per-topic instance/dialog lists."""
    assert data_type in ("wow_seen", "wow_unseen", "woi")
    test_topics = set()
    with open(test_datapath) as f:
        for line in f:
            test_topics.add(line.strip().split("\t")[0])

    train_data_by_topic: dict = {}
    dialog_data_by_topic: dict = {}
    dialog_examples = []
    with open(train_datapath) as f:
        for line in f:
            splits = line.strip().split("\t")
            topic = splits[0]
            turns = splits[1].split(" [SEP] ")[-3:]
            knowledge = splits[2]
            if knowledge == "no_passages_used":
                continue
            if data_type != "wow_seen" and ("(" in knowledge
                                            or ")" in knowledge):
                continue
            if data_type != "wow_seen" and topic not in knowledge:
                continue
            last_turn = turns[-1]
            instance = f"( {last_turn} ) {topic} => {knowledge}"
            dialog_example = ""
            if data_type != "wow_seen":
                dialog_example += f"( {topic} ) "
            dialog_example += " ".join(turns)

            if topic in test_topics:
                train_data_by_topic.setdefault(topic, []).append(instance)
                dialog_data_by_topic.setdefault(topic, []).append(
                    dialog_example)
            else:
                if len(knowledge.split()) > 20:
                    continue
                if knowledge.lower().startswith(("it", "this")):
                    continue
            dialog_examples.append((topic, dialog_example, instance))
    return train_data_by_topic, dialog_data_by_topic, dialog_examples


def _lexical_similarity(query: str, candidates):
    """Token-F1 overlap ranking (the no-checkpoint default; the reference
    ranks with a DPR encoder, ref :323-362)."""
    qn = normalize_answer(query)
    scores = []
    for cand in candidates:
        _, _, f1 = f1_score(qn, cand)
        scores.append(f1 if f1 is not None else 0.0)
    return np.asarray(scores)


def prompt_selection_for_knowledge_generation(
    test_datapath, train_datapath, output_prompt_path,
    data_type="wow_seen", topk: int = 10, encode_fn=None,
):
    """Per test sample: top-k most relevant training instances of the same
    topic, written as jsonl {key: [prompt instances]} with key =
    `topic + " " + last_turn` (ref :364-460). `encode_fn(texts)->(n,d)`
    switches ranking to embedding dot products (the reference's DPR
    form); default is lexical overlap."""
    train_by_topic, dialog_by_topic, _ = get_database(
        test_datapath, train_datapath, data_type
    )

    with open(test_datapath) as f, open(output_prompt_path, "w") as fout:
        seen = set()
        for line in f:
            splits = line.strip().split("\t")
            topic = splits[0]
            last_turn = splits[1].split(" [SEP] ")[-1]
            key = topic + " " + last_turn
            if key in seen:
                continue
            seen.add(key)
            instances = train_by_topic.get(topic, [])
            dialogs = dialog_by_topic.get(topic, [])
            if not instances:
                fout.write(json.dumps({key: []}) + "\n")
                continue
            query = (f"( {topic} ) " if data_type != "wow_seen" else "") \
                + last_turn
            if encode_fn is not None:
                qv = np.asarray(encode_fn([query]))[0]
                dv = np.asarray(encode_fn(dialogs))
                scores = dv @ qv
            else:
                scores = _lexical_similarity(query, dialogs)
            order = np.argsort(-scores)[:topk]
            # most-similar LAST (the reference appends nearest at the end,
            # closest to the test input)
            chosen = [instances[i] for i in order[::-1]]
            fout.write(json.dumps({key: chosen}) + "\n")


def prompt_selection_for_response_generation(input_path, output_path,
                                             seed: int = 1234,
                                             num_prompts: int = 20):
    """Seeded random selection of response-generation prompt lines in the
    `Topic: .. User says: .. We know that: .. System replies: ..` form
    (ref :462-531)."""
    rows = []
    with open(input_path) as f:
        for line in f:
            splits = line.strip().split("\t")
            topic, context, knowledge, response = (
                splits[0], splits[1], splits[2], splits[3])
            if knowledge == "no_passages_used":
                continue
            last_turn = " ".join(word_tokenize(
                context.split(" [SEP] ")[-1]))
            knowledge = " ".join(word_tokenize(knowledge))
            response = " ".join(word_tokenize(response))
            rows.append(
                f"Topic: {topic}. User says: {last_turn} We know that: "
                f"{knowledge} System replies: {response}"
            )
    rng = np.random.RandomState(seed)
    idx = rng.permutation(len(rows))[:num_prompts]
    with open(output_path, "w") as f:
        for i in idx:
            f.write(rows[int(i)] + "\n")


def prepare_input_for_response_generation(test_file, knwl_gen_file,
                                          processed_file):
    """Merge generated knowledge into the test file (ref :533-559)."""
    with open(knwl_gen_file) as f:
        knowledge_list = f.readlines()
    with open(test_file) as fr, open(processed_file, "w") as fw:
        for line_num, line in enumerate(fr):
            splits = line.strip().split("\t")
            topic, dialog_context, response = (splits[0], splits[1],
                                               splits[3])
            knowledge = knowledge_list[line_num].strip().replace(
                "<|endoftext|>", "")
            fw.write(f"{topic}\t{dialog_context}\t{knowledge}"
                     f"\t{response}\n")
