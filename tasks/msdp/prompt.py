"""Multi-stage dialogue prompting: knowledge + response generation.

Parity target: ref tasks/msdp/prompt.py — few-shot prompt a pretrained GPT
to generate (stage 1) the grounding knowledge for the last user turn and
(stage 2) the system response given that knowledge, reading the
preprocessing.py file formats:

- test file: `topic \\t context [SEP]-joined \\t knowledge \\t response`;
- knowledge prompts: jsonl {topic + " " + last_turn: [instances]};
- response prompts: plain lines, first --num_prompt_examples used.

The reference drives its per-token pipeline loop (or a REST api,
:19-36); here each constructed input goes through the jitted generation
engine via `generate_and_post_process`, taking the first line of the
completion (ref truncates at "\\n", :33-35).
"""

from __future__ import annotations

import json

from tasks.msdp.preprocessing import word_tokenize


def read_prompts(prompt_path, prompt_type, n_example):
    """ref: prompt.py:38-71."""
    if prompt_type == "knowledge":
        prompt_examples_dict = {}
        with open(prompt_path) as f:
            for line in f:
                line_dict = json.loads(line.strip())
                key = list(line_dict.keys())[0]
                if key not in prompt_examples_dict:
                    prompt = ""
                    for instance in line_dict[key]:
                        prompt += instance.strip() + " \n"
                    prompt_examples_dict[key] = prompt
        return prompt_examples_dict
    prompt = ""
    with open(prompt_path) as f:
        for instance in f.readlines()[:n_example]:
            prompt += instance.strip() + " \n"
    return prompt


def build_input(test_sample: str, prompt_type: str, prompts):
    """One test line -> the full few-shot input string
    (ref: prompt.py:95-130 / 215-260)."""
    splits = test_sample.strip().split("\t")
    topic = splits[0]
    turns = splits[1].split(" [SEP] ")
    last_turn = turns[-1]
    if prompt_type == "knowledge":
        key = topic + " " + last_turn
        inputs = prompts.get(key, "") if isinstance(prompts, dict) \
            else prompts
        inputs += "( " + last_turn + " ) " + topic + " =>"
        return inputs
    knowledge = splits[2]
    last_turn = " ".join(word_tokenize(last_turn)).strip()
    knowledge = " ".join(word_tokenize(knowledge)).strip()
    inputs = prompts
    inputs += (f"Topic: {topic}. User says: {last_turn} We know that: "
               f"{knowledge} System replies:")
    return inputs


def generate_samples_from_file(
    model, params, tokenizer, sample_input_file, sample_output_file,
    prompt_file, prompt_type, num_prompt_examples: int = 10,
    out_seq_length: int = 100,
):
    """Prompt the model over every test line (ref: prompt.py:154-290).
    Greedy (top_k=1) like the reference's api mode; one line of the
    completion is kept."""
    from megatron_llm_tpu.inference.api import generate_and_post_process

    assert prompt_type in ("knowledge", "response")
    prompts = read_prompts(prompt_file, prompt_type, num_prompt_examples)

    with open(sample_input_file) as f:
        test_samples = [ln for ln in f.read().splitlines() if ln.strip()]

    with open(sample_output_file, "w") as fout:
        for sample in test_samples:
            inputs = build_input(sample, prompt_type, prompts)
            texts, _, _, _ = generate_and_post_process(
                model, params, tokenizer, [inputs],
                tokens_to_generate=out_seq_length, top_k_sampling=1,
            )
            completion = texts[0][len(inputs):]
            completion = completion.split("\n")[0].strip()
            completion = completion.replace("<|endoftext|>", "")
            fout.write(completion + "\n")
    return sample_output_file


def main(args, model=None, params=None, tokenizer=None):
    """Dispatch target for tasks/main.py --task MSDP-PROMPT."""
    return generate_samples_from_file(
        model, params, tokenizer,
        args.sample_input_file, args.sample_output_file,
        args.prompt_file, args.prompt_type,
        num_prompt_examples=args.num_prompt_examples,
        out_seq_length=args.out_seq_length,
    )
