"""Dialog evaluation metrics: normalized token-level F1.

Parity target: ref tasks/msdp/metrics.py (itself adapted from ParlAI) —
lowercase, strip punctuation/articles, whitespace-split, then
precision/recall/F1 over token multisets, averaged over pairs with
non-empty gold answers.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import List, Optional, Tuple

import numpy as np

_RE_ART = re.compile(r"\b(a|an|the)\b")
_RE_PUNC = re.compile(r"[!\"#$%&()*+,-./:;<=>?@\[\]\\^`{|}~_']")


def normalize_answer(s: str) -> str:
    """Lowercase; drop punctuation, articles and extra whitespace
    (ref: metrics.py:17-25)."""
    s = s.lower()
    s = _RE_PUNC.sub(" ", s)
    s = _RE_ART.sub(" ", s)
    return " ".join(s.split())


def _prec_recall_f1(pred_items, gold_items) -> Tuple[float, float, float]:
    common = Counter(gold_items) & Counter(pred_items)
    num_same = sum(common.values())
    if num_same == 0:
        return 0.0, 0.0, 0.0
    precision = num_same / len(pred_items)
    recall = num_same / len(gold_items)
    return precision, recall, 2 * precision * recall / (precision + recall)


def f1_score(guess: str, answer: str
             ) -> Tuple[Optional[float], Optional[float], Optional[float]]:
    """(precision, recall, f1) for one pair; (None,)*3 when the gold
    answer is empty (excluded from averaging, ref: metrics.py:52-60)."""
    if answer == "":
        return None, None, None
    if guess == "":
        return 0.0, 0.0, 0.0
    return _prec_recall_f1(normalize_answer(guess).split(),
                           normalize_answer(answer).split())


def f1_score_all(guesses: List[str], answers: List[str]
                 ) -> Tuple[float, float, float]:
    """Mean (precision, recall, f1) over pairs (ref: metrics.py:62-76)."""
    assert len(guesses) == len(answers), (len(guesses), len(answers))
    ps, rs, fs = [], [], []
    for guess, answer in zip(guesses, answers):
        p, r, f = f1_score(guess, answer)
        if p is None:
            continue
        ps.append(p)
        rs.append(r)
        fs.append(f)
    return float(np.mean(ps)), float(np.mean(rs)), float(np.mean(fs))
