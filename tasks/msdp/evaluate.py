"""MSDP F1 evaluation over guess/answer files.

Parity target: ref tasks/msdp/evaluate.py — one guess and one gold answer
per line; `<|endoftext|>` stripped from guesses, the gold placeholder
`no_passages_used` counts as an empty answer (excluded from the average).
"""

from __future__ import annotations

from tasks.msdp.metrics import f1_score_all


def evaluate_f1(guess_file: str, answer_file: str):
    """Returns (precision, recall, f1) (ref: evaluate.py:12-38)."""
    guesses = []
    with open(guess_file) as f:
        for line in f:
            line = line.strip().replace("<|endoftext|>", "")
            guesses.append(line)

    answers = []
    with open(answer_file) as f:
        for line in f:
            line = line.strip()
            if line == "no_passages_used":
                line = ""
            answers.append(line)

    precision, recall, f1 = f1_score_all(guesses, answers)
    print(f"Precision: {precision:.4f}; recall: {recall:.4f}; "
          f"f1: {f1:.4f}", flush=True)
    return precision, recall, f1


def main(args):
    return evaluate_f1(args.guess_file, args.answer_file)
