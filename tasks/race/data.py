"""RACE multiple-choice dataset (ref: tasks/race/data.py).

Each question yields NUM_CHOICES samples of [CLS] article [SEP]
question+option [SEP]; the model scores each and softmaxes over the four
(models/classification.MultipleChoice). Inputs are RACE-format .txt JSON
files: {"article", "questions", "options", "answers"}.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from tasks.data_utils import (
    build_tokens_types_paddings_from_text,
    clean_text,
)

NUM_CHOICES = 4


class RaceDataset:

    def __init__(self, dataset_name, datapaths, tokenizer, max_seq_length):
        self.dataset_name = dataset_name
        self.tokenizer = tokenizer
        self.max_seq_length = max_seq_length
        self.samples = []
        for path in datapaths:
            self.samples.extend(self._process_path(path))
        print(f" > {dataset_name}: {len(self.samples)} RACE questions",
              flush=True)

    def _process_path(self, path):
        files = ([path] if os.path.isfile(path)
                 else sorted(glob.glob(os.path.join(path, "**", "*.txt"),
                                       recursive=True)))
        samples = []
        for fname in files:
            with open(fname) as f:
                data = json.load(f)
            article = clean_text(data["article"])
            for q, opts, ans in zip(data["questions"], data["options"],
                                    data["answers"]):
                label = ord(ans) - ord("A")
                assert 0 <= label < NUM_CHOICES
                assert len(opts) == NUM_CHOICES
                samples.append({
                    "article": article,
                    "texts_b": [clean_text(f"{q} {o}") for o in opts],
                    "label": label,
                    "uid": len(samples),
                })
        return samples

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        raw = self.samples[idx]
        ids_c, types_c, pad_c = [], [], []
        for text_b in raw["texts_b"]:
            ids, types, paddings = build_tokens_types_paddings_from_text(
                raw["article"], text_b, self.tokenizer, self.max_seq_length
            )
            ids_c.append(ids)
            types_c.append(types)
            pad_c.append(paddings)
        return {
            "text": np.array(ids_c, np.int64),  # (4, s)
            "types": np.array(types_c, np.int64),
            "padding_mask": np.array(pad_c, np.int64),
            "label": int(raw["label"]),
            "uid": int(raw["uid"]),
        }
