"""Epoch-based classification finetuning shared by GLUE and RACE.

Parity target: ref tasks/finetune_utils.py:141-337 — epoch loop over a
shuffled train set, LR warmup+decay over total steps, per-epoch
validation accuracy, best-checkpoint save. TPU-first: one jitted
(loss+grad+Adam) step and one jitted accuracy step; the host only stacks
numpy batches.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.optimizer import init_optimizer_state
from megatron_llm_tpu.optimizer.optimizer import optimizer_step
from megatron_llm_tpu.optimizer.scheduler import OptimizerParamScheduler


def _stack_batch(samples):
    # RACE samples already carry a (num_choices, s) leading axis; stacking
    # is identical for both task shapes
    return {
        "tokens": np.stack([s["text"] for s in samples]).astype(np.int32),
        "attention_mask": np.stack(
            [s["padding_mask"] for s in samples]
        ).astype(np.int32),
        "tokentype_ids": np.stack([s["types"] for s in samples]).astype(
            np.int32
        ),
        "labels": np.asarray([s["label"] for s in samples], np.int32),
    }


def _batches(dataset, batch_size, rng=None, drop_last=True):
    order = np.arange(len(dataset))
    if rng is not None:
        rng.shuffle(order)
    end = (len(order) // batch_size * batch_size if drop_last
           else len(order))
    for i in range(0, end, batch_size):
        idxs = order[i:i + batch_size]
        yield [dataset[int(j)] for j in idxs]


def accuracy(model, params, dataset, batch_size: int) -> float:
    """ref: calculate_correct_answers (eval_utils.py) — exact-match
    accuracy over the whole set, jitted argmax per batch. The jitted fn
    is cached on the model object so repeated calls (one per validation
    epoch) reuse one compilation."""
    correct = model.__dict__.get("_accuracy_step")
    if correct is None:
        @jax.jit
        def correct(params, batch):
            logits = model.forward(
                params, batch["tokens"], batch["attention_mask"],
                batch["tokentype_ids"],
            )
            return jnp.sum(jnp.argmax(logits, -1) == batch["labels"])

        model.__dict__["_accuracy_step"] = correct

    total = n = 0
    for samples in _batches(dataset, batch_size, drop_last=False):
        batch = {k: jnp.asarray(v)
                 for k, v in _stack_batch(samples).items()}
        total += int(correct(params, batch))
        n += len(samples)
    return total / max(n, 1)


def finetune(model, params, train_ds, valid_ds, *, epochs: int,
             batch_size: int, lr: float, weight_decay: float = 0.01,
             warmup_fraction: float = 0.065, seed: int = 1234,
             tcfg=None, log_interval: int = 50):
    """Run the finetune loop; returns (best-epoch params — last-epoch when
    no validation set — and the best validation accuracy)
    (ref: finetune_utils.finetune :241-337)."""
    from megatron_llm_tpu.config import TrainConfig

    tcfg = tcfg or TrainConfig(micro_batch_size=batch_size,
                               global_batch_size=batch_size, lr=lr,
                               weight_decay=weight_decay)
    opt_state = init_optimizer_state(params, tcfg)
    steps_per_epoch = len(train_ds) // batch_size
    total_steps = max(1, epochs * steps_per_epoch)
    sched = OptimizerParamScheduler(
        max_lr=lr, min_lr=0.0,
        lr_warmup_steps=int(warmup_fraction * total_steps),
        lr_decay_steps=total_steps, lr_decay_style="linear",
        start_wd=weight_decay, end_wd=weight_decay, wd_incr_steps=total_steps,
        wd_incr_style="constant",
    )

    @jax.jit
    def step(params, opt_state, batch, lr_now, dropout_rng):
        def loss_fn(p):
            return model.loss(
                p, batch["tokens"], batch["labels"],
                attention_mask=batch["attention_mask"],
                tokentype_ids=batch["tokentype_ids"],
                dropout_rng=dropout_rng,
                deterministic=False,
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state, stats = optimizer_step(
            params, grads, opt_state, tcfg, lr_now,
            weight_decay=jnp.float32(weight_decay),
        )
        stats["loss"] = loss
        return params, opt_state, stats

    # DP > 1: shard batches over the data axis and replicate params so the
    # jitted step runs GSPMD data-parallel (batches are host-built)
    from megatron_llm_tpu.parallel.mesh import DATA_AXIS, get_context

    ctx = get_context()
    batch_sharding = None
    if ctx is not None and ctx.dp > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        # batch_size is the GLOBAL batch: each of the dp devices computes
        # batch_size/dp samples of it (same convention as the pretraining
        # loader's mbs*dp global microbatch)
        assert batch_size % ctx.dp == 0, (
            f"batch size {batch_size} must divide dp={ctx.dp}"
        )
        params = jax.device_put(
            params, jax.tree.map(lambda _: NamedSharding(ctx.mesh, P()),
                                 params),
        )
        batch_sharding = lambda v: jax.device_put(  # noqa: E731
            v, NamedSharding(ctx.mesh,
                             P(DATA_AXIS, *([None] * (v.ndim - 1)))),
        )

    rng = np.random.RandomState(seed)
    dropout_key = jax.random.key(seed + 1)
    best_acc, best_params, it = 0.0, None, 0
    for epoch in range(epochs):
        t0 = time.time()
        for samples in _batches(train_ds, batch_size, rng=rng):
            batch = {k: jnp.asarray(v)
                     for k, v in _stack_batch(samples).items()}
            if batch_sharding is not None:
                batch = {k: batch_sharding(v) for k, v in batch.items()}
            # advance first so step 1 trains at max_lr/warmup_steps, not 0
            # (the reference increments num_steps before applying the lr)
            sched.step()
            params, opt_state, stats = step(
                params, opt_state, batch, jnp.float32(sched.get_lr()),
                jax.random.fold_in(dropout_key, it),
            )
            it += 1
            if it % log_interval == 0:
                print(f"epoch {epoch} iter {it}/{total_steps} | "
                      f"loss {float(stats['loss']):.4f} | "
                      f"lr {sched.get_lr():.3E}", flush=True)
        if valid_ds is not None and len(valid_ds):
            acc = accuracy(model, params, valid_ds, batch_size)
            if acc >= best_acc:
                best_acc, best_params = acc, params
            print(f"epoch {epoch} done in {time.time()-t0:.1f}s | "
                  f"validation accuracy: {acc:.4f}", flush=True)
    return (best_params if best_params is not None else params), best_acc
