"""Dataset-specific detokenizers for zero-shot LM eval.

Parity target: ref tasks/zeroshot_gpt/detokenizer.py. The rules are data
contracts (they undo PTB/WikiText tokenizer artifacts so the model sees
natural text and the token-ratio adjustment stays comparable across
papers), so the REPLACEMENTS must match the reference rule-for-rule; the
implementation is table-driven instead of a statement list.
"""

from __future__ import annotations

import re

# (pattern, replacement, is_regex)
_PTB_RULES = [
    (" '", "'", False),
    (" \n", "\n", False),
    ("\n ", "\n", False),
    (" n't", "n't", False),
    (" N ", "1 ", False),
    ("$ 1", "$1", False),
    ("# 1", "#1", False),
]

_WIKITEXT_RULES = [
    # contractions
    ("s '", "s'", False),
    (r"/' [0-9]/", r"/'[0-9]/", True),
    # number separators
    (" @-@ ", "-", False),
    (" @,@ ", ",", False),
    (" @.@ ", ".", False),
    # punctuation
    (" : ", ": ", False),
    (" ; ", "; ", False),
    (" . ", ". ", False),
    (" ! ", "! ", False),
    (" ? ", "? ", False),
    (" , ", ", ", False),
    # double brackets
    (r"\(\s*([^\)]*?)\s*\)", r"(\1)", True),
    (r"\[\s*([^\]]*?)\s*\]", r"[\1]", True),
    (r"{\s*([^}]*?)\s*}", r"{\1}", True),
    (r"\"\s*([^\"]*?)\s*\"", r'"\1"', True),
    (r"'\s*([^']*?)\s*'", r"'\1'", True),
    # miscellaneous
    ("= = = =", "====", False),
    ("= = =", "===", False),
    ("= =", "==", False),
    (" " + chr(176) + " ", chr(176), False),
    (" \n", "\n", False),
    ("\n ", "\n", False),
    (" N ", " 1 ", False),
    (" 's", "'s", False),
]


def _apply(rules, text: str) -> str:
    for pat, repl, is_regex in rules:
        text = re.sub(pat, repl, text) if is_regex else text.replace(pat, repl)
    return text


def ptb_detokenizer(text: str) -> str:
    return _apply(_PTB_RULES, text)


def wikitext_detokenizer(text: str) -> str:
    return _apply(_WIKITEXT_RULES, text)


def lambada_detokenizer(text: str) -> str:
    return text


_DETOKENIZERS = {
    "ptb": ptb_detokenizer,
    "wiki": wikitext_detokenizer,
    "lambada": lambada_detokenizer,
}


def get_detokenizer(path: str):
    """Pick by substring of the data path (ref: detokenizer.py:62-68)."""
    for key, fn in _DETOKENIZERS.items():
        if key in path:
            return fn
    return lambda s: s
