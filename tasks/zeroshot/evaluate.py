"""Zero-shot GPT evaluation: WikiText-103 perplexity, LAMBADA accuracy.

Parity target: ref tasks/zeroshot_gpt/evaluate.py. The reference drives a
torch DataLoader through per-rank forward steps with pipeline send/recv
and a DP all-reduce; here the eval set is fixed-shape arrays and ONE
jitted step per batch computes either the masked loss sum ('loss' metric)
or the number of fully-correct cloze samples ('accuracy' metric) — under
GSPMD the same step runs sharded on any mesh with no explicit collectives.
"""

from __future__ import annotations

import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)


def metric_for_task(task: str) -> str:
    if task == "LAMBADA":
        return "accuracy"
    if task == "WIKITEXT103":
        return "loss"
    raise NotImplementedError(f"{task} task is not implemented.")


def make_eval_step(model, eval_metric: str):
    """Batch step -> scalar contribution (ref: forward_step
    evaluate.py:74-113)."""

    @jax.jit
    def step(params, tokens, pad_mask):
        # tokens (b, s+1); pad_mask (b, s)
        inp, labels = tokens[:, :-1], tokens[:, 1:]
        logits, _ = model.forward(params, inp)
        if eval_metric == "loss":
            losses = vocab_parallel_cross_entropy(logits, labels)
            return jnp.sum(losses * pad_mask)
        if eval_metric == "accuracy":
            pred = jnp.argmax(logits, axis=-1)
            correct = (pred == labels) | (pad_mask == 0.0)
            # a sample counts only if every scored position is right
            # (ref: evaluate.py:106-110 correct.prod(-1))
            sample_ok = jnp.all(correct, axis=-1)
            # fully-padded filler rows (batch pad) score 0
            real = jnp.any(pad_mask > 0.0, axis=-1)
            return jnp.sum((sample_ok & real).astype(jnp.float32))
        raise NotImplementedError(eval_metric)

    return step


def evaluate(model, params, data, eval_metric: str,
             micro_batch_size: int = 8, log_interval: int = 100) -> float:
    """ref: evaluate (evaluate.py:116-139). Pads the sample count up to a
    batch multiple with zero-mask rows so every step compiles once."""
    step = make_eval_step(model, eval_metric)
    n = len(data)
    b = micro_batch_size
    n_pad = (-n) % b
    tokens = np.concatenate(
        [data.tokens, np.zeros((n_pad,) + data.tokens.shape[1:], np.int32)]
    )
    mask = np.concatenate(
        [data.pad_mask, np.zeros((n_pad,) + data.pad_mask.shape[1:],
                                 np.float32)]
    )
    total = 0.0
    t0 = time.perf_counter()
    for it in range(0, len(tokens), b):
        if (it // b) % log_interval == 0:
            print(f"> working on iteration: {it // b}", flush=True)
        total += float(step(params, jnp.asarray(tokens[it:it + b]),
                            jnp.asarray(mask[it:it + b])))
    dt = time.perf_counter() - t0
    print(f"> evaluated {n} samples in {dt:.1f}s", flush=True)
    return total


def evaluate_and_print_results(task: str, model, params, data,
                               micro_batch_size: int = 8,
                               log_interval: int = 100) -> dict:
    """ref: _evaluate_and_print_results (evaluate.py:142-176) — same
    result-line format, returns the metrics dict for tests."""
    eval_metric = metric_for_task(task)
    output = evaluate(model, params, data, eval_metric, micro_batch_size,
                      log_interval)

    string = f" validation results on {task} | "
    out: dict = {}
    if eval_metric == "loss":
        num_tokenized_tokens = data.num_tokenized_tokens
        num_original_tokens = data.num_original_tokens
        val_loss = output / (num_tokenized_tokens - 1)
        ppl = math.exp(min(20, val_loss))
        token_ratio = (num_tokenized_tokens - 1) / (num_original_tokens - 1)
        adjusted_ppl = math.exp(min(20, val_loss * token_ratio))
        out = {"avg_loss": val_loss, "ppl": ppl,
               "adjusted_ppl": adjusted_ppl, "token_ratio": token_ratio}
        string += f"avg loss: {val_loss:.4E} | "
        string += f"ppl: {ppl:.4E} | "
        string += f"adjusted ppl: {adjusted_ppl:.4E} | "
        string += f"token ratio: {token_ratio} |"
    else:
        num_examples = len(data)
        acc = output / num_examples
        out = {"num_correct": output, "num_examples": num_examples,
               "accuracy": acc}
        string += f"number correct: {output:.4E} | "
        string += f"total examples: {num_examples:.4E} | "
        string += f"avg accuracy: {acc:.4E}"

    length = len(string) + 1
    print("-" * length)
    print(string)
    print("-" * length, flush=True)
    return out
