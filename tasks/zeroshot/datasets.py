"""Zero-shot eval datasets as stacked numpy arrays.

Parity target: ref tasks/zeroshot_gpt/datasets.py — the sliding-window LM
dataset (WikiText-103 ppl) and the LAMBADA cloze dataset. The reference
yields per-sample dicts through a torch DataLoader; on TPU the whole eval
set is materialised as (N, seq+1) int32 / (N, seq) mask arrays up front so
the jitted eval step runs over fixed-shape batches with zero host work in
the loop.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass

import numpy as np

from tasks.zeroshot.detokenizer import get_detokenizer


@dataclass
class EvalData:
    """tokens (N, seq+1) int32; pad_mask (N, seq) float32 (1 = scored)."""

    tokens: np.ndarray
    pad_mask: np.ndarray
    num_original_tokens: int = 0
    num_tokenized_tokens: int = 0

    def __len__(self):
        return self.tokens.shape[0]


def build_lm_dataset(tokens, seq_len: int, pad_idx: int,
                     num_original_tokens: int, num_tokenized_tokens: int,
                     overlapping_eval: int | None = None) -> EvalData:
    """Sliding-window LM eval windows (ref: _LMDataset datasets.py:28-65).

    Window i starts at i*overlap; with overlap < seq_len only the last
    `overlap` targets of each non-first window are scored (the rest are
    context), reproducing the reference's pad_mask zeroing.
    """
    tokens = list(tokens)
    if overlapping_eval is None:
        overlapping_eval = seq_len
    overlapping_eval = max(1, overlapping_eval)
    total_targets = len(tokens) - 1
    targets = max(total_targets - overlapping_eval, 0)
    total_sequences = max(math.ceil(targets / overlapping_eval) + 1, 1)

    toks = np.full((total_sequences, seq_len + 1), pad_idx, np.int32)
    mask = np.zeros((total_sequences, seq_len), np.float32)
    for idx in range(total_sequences):
        start = idx * overlapping_eval
        window = tokens[start:start + seq_len + 1]
        n = len(window)
        toks[idx, :n] = window
        mask[idx, : max(n - 1, 0)] = 1.0
        if overlapping_eval != seq_len and idx != 0:
            mask[idx, :-overlapping_eval] = 0.0
    return EvalData(toks, mask, num_original_tokens, num_tokenized_tokens)


def build_wikitext_dataset(path: str, tokenizer, seq_len: int,
                           overlapping_eval: int | None = None) -> EvalData:
    """ref: _build_wikitext103_dataset (datasets.py:127-146): whole-file
    detokenize -> tokenize -> sliding windows; token ratio feeds the
    adjusted-ppl number."""
    with open(path, "rb") as f:
        raw = f.read().decode("utf-8")
    num_original_tokens = len(raw.strip().split(" "))
    text = get_detokenizer(path)(raw)
    ids = tokenizer.tokenize(text)
    return build_lm_dataset(
        ids, seq_len, tokenizer.eod, num_original_tokens, len(ids),
        overlapping_eval,
    )


def build_lambada_dataset(path: str, tokenizer, seq_len: int,
                          strict: bool = False) -> EvalData:
    """ref: _LambadaDataset (datasets.py:68-113): jsonl of {"text": ...};
    score only the final word's token(s). `strict` re-splits the last
    whitespace word and tokenizes it with a leading space (the harder,
    paper-faithful formulation)."""
    toks_rows, mask_rows = [], []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            text = json.loads(line)["text"]
            if strict:
                last_word = text.split()[-1]
                start = text.rfind(last_word)
                context = tokenizer.tokenize(text[:start].strip())
                answer = tokenizer.tokenize(" " + last_word)
            else:
                ids = tokenizer.tokenize(text)
                context, answer = ids[:-1], [ids[-1]]
            row = context + answer
            mask = [0.0] * len(context) + [1.0] * len(answer)
            if len(row) > seq_len + 1:
                # left-truncate CONTEXT so the scored answer tokens always
                # survive (right-truncating would silently zero the mask
                # and make the sample unwinnable)
                row = row[-(seq_len + 1):]
                mask = mask[-(seq_len + 1):]
            elif len(row) < seq_len + 1:
                pad = seq_len + 1 - len(row)
                row = row + [tokenizer.eod] * pad
                mask = mask + [0.0] * pad
            toks_rows.append(row)
            mask_rows.append(mask[1:])
    return EvalData(
        np.asarray(toks_rows, np.int32),
        np.asarray(mask_rows, np.float32),
    )


def build_dataset(task: str, valid_data: str, tokenizer, seq_len: int,
                  overlapping_eval: int | None = None,
                  strict_lambada: bool = False) -> EvalData:
    """ref: build_dataset (datasets.py:17-25)."""
    if task == "LAMBADA":
        return build_lambada_dataset(valid_data, tokenizer, seq_len,
                                     strict_lambada)
    if task == "WIKITEXT103":
        return build_wikitext_dataset(valid_data, tokenizer, seq_len,
                                      overlapping_eval)
    raise NotImplementedError(f"dataset for {task} task is not implemented.")
