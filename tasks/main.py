#!/usr/bin/env python
"""Downstream-task entry point (ref: /root/reference/tasks/main.py).

  python tasks/main.py --task WIKITEXT103 --model_name llama2 \\
      --valid_data wiki.test.tokens --tokenizer_type SentencePieceTokenizer \\
      --tokenizer_model tokenizer.model --load <checkpoint_dir>

  python tasks/main.py --task LAMBADA --valid_data lambada.jsonl ...

Without --load the model evaluates at random init (useful for smoke runs
only). The retriever/Race/MNLI finetune family of the reference is not
implemented (matching its own 'not supported' carve-outs for non-GPT
models, main.py:80-100).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__),
                                                os.pardir)))

import jax


def get_tasks_args(parser):
    """ref: get_tasks_args (tasks/main.py:14-72), minus the retriever/faiss
    group that belongs to the unimplemented ICT stack."""
    g = parser.add_argument_group("tasks")
    g.add_argument("--task", type=str, required=True,
                   choices=["WIKITEXT103", "LAMBADA"])
    g.add_argument("--valid_data", nargs="*", default=None)
    g.add_argument("--overlapping_eval", type=int, default=32)
    g.add_argument("--strict_lambada", action="store_true")
    g.add_argument("--eval_micro_batch_size", type=int, default=None)
    return parser


def main(argv=None):
    from megatron_llm_tpu.arguments import args_to_configs, build_base_parser
    from megatron_llm_tpu.parallel import initialize_parallel
    from megatron_llm_tpu.tokenizer import build_tokenizer
    from megatron_llm_tpu.training.checkpointing import load_checkpoint

    from finetune import model_provider
    from tasks.zeroshot.datasets import build_dataset
    from tasks.zeroshot.evaluate import evaluate_and_print_results

    parser = get_tasks_args(build_base_parser())
    args = parser.parse_args(argv)
    assert args.valid_data and len(args.valid_data) == 1, \
        "--valid_data takes exactly one path"

    tokenizer = build_tokenizer(
        args.tokenizer_type or "NullTokenizer",
        vocab_file=args.vocab_file,
        merges_file=args.merges_file,
        tokenizer_model=args.tokenizer_model,
        make_vocab_size_divisible_by=args.make_vocab_size_divisible_by,
        tensor_parallel_size=args.tensor_model_parallel_size,
        null_vocab_size=args.null_vocab_size,
    )
    mcfg, pcfg, tcfg, _ = args_to_configs(args, tokenizer.vocab_size)

    initialize_parallel(
        dp=pcfg.data_parallel_size,
        pp=pcfg.pipeline_parallel_size,
        tp=pcfg.tensor_parallel_size,
        sequence_parallel=pcfg.sequence_parallel,
    )

    model = model_provider(args, mcfg)
    params = model.init(jax.random.key(tcfg.seed))
    if args.load:
        restored = load_checkpoint(args.load, params, model_cfg=mcfg,
                                   no_load_optim=True)
        assert restored is not None, f"no checkpoint found in {args.load}"
        params = restored[0]

    data = build_dataset(
        args.task, args.valid_data[0], tokenizer, mcfg.seq_length,
        overlapping_eval=args.overlapping_eval,
        strict_lambada=args.strict_lambada,
    )
    print(f" > found {len(data)} samples.")
    evaluate_and_print_results(
        args.task, model, params, data,
        micro_batch_size=args.eval_micro_batch_size or args.micro_batch_size,
        log_interval=args.log_interval,
    )
    print("done :-)")


if __name__ == "__main__":
    main()
