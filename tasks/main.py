#!/usr/bin/env python
"""Downstream-task entry point (ref: /root/reference/tasks/main.py).

  python tasks/main.py --task WIKITEXT103 --model_name llama2 \\
      --valid_data wiki.test.tokens --tokenizer_type SentencePieceTokenizer \\
      --tokenizer_model tokenizer.model --load <checkpoint_dir>

  python tasks/main.py --task LAMBADA --valid_data lambada.jsonl ...

Classification finetuning (BERT encoder + task head, epoch loop with
per-epoch validation accuracy):

  python tasks/main.py --task MNLI --train_data train.tsv \\
      --valid_data dev_matched.tsv --pretrained_checkpoint ckpts/bert \\
      --epochs 3 --lr 5e-5 ...   (QQP and RACE likewise)

Without --load / --pretrained_checkpoint the model runs at random init
(useful for smoke runs only). The REALM/retriever finetune family is not
implemented.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__),
                                                os.pardir)))

import jax


def get_tasks_args(parser):
    """ref: get_tasks_args (tasks/main.py:14-72), minus the retriever/faiss
    group that belongs to the REALM stack."""
    g = parser.add_argument_group("tasks")
    g.add_argument("--task", type=str, required=True,
                   choices=["WIKITEXT103", "LAMBADA", "MNLI", "QQP", "RACE",
                            "MSDP-PROMPT", "MSDP-EVAL-F1",
                            "RETRIEVER-EVAL", "ICT-ZEROSHOT-NQ",
                            "RET-FINETUNE-NQ"])
    g.add_argument("--train_data", nargs="+", default=None)
    g.add_argument("--valid_data", nargs="*", default=None)
    g.add_argument("--overlapping_eval", type=int, default=32)
    g.add_argument("--strict_lambada", action="store_true")
    g.add_argument("--eval_micro_batch_size", type=int, default=None)
    g.add_argument("--epochs", type=int, default=3)
    g.add_argument("--pretrained_checkpoint", type=str, default=None)
    # MSDP (ref: tasks/msdp/main.py get_tasks_args)
    g.add_argument("--sample_input_file", type=str, default=None)
    g.add_argument("--sample_output_file", type=str, default=None)
    g.add_argument("--prompt_file", type=str, default=None)
    g.add_argument("--prompt_type", type=str, default=None,
                   choices=[None, "knowledge", "response"])
    g.add_argument("--num_prompt_examples", type=int, default=10)
    g.add_argument("--guess_file", type=str, default=None)
    g.add_argument("--answer_file", type=str, default=None)
    g.add_argument("--out_seq_length", type=int, default=100)
    # ORQA retriever eval (ref: tasks/main.py:56-72 + orqa args)
    g.add_argument("--qa_data_dev", type=str, default=None)
    g.add_argument("--qa_data_test", type=str, default=None)
    g.add_argument("--evidence_data_path", type=str, default=None)
    # prebuilt evidence index (tools/build_retrieval_index.py output);
    # omitted -> embed the evidence on the fly
    g.add_argument("--embedding_path", type=str, default=None)
    g.add_argument("--retriever_seq_length", type=int, default=256)
    g.add_argument("--retriever_topk", type=int, default=20)
    g.add_argument("--match", type=str, default="string",
                   choices=["string", "regex"])
    g.add_argument("--biencoder_shared_query_context_model",
                   action="store_true")
    g.add_argument("--biencoder_projection_dim", type=int, default=0)
    g.add_argument("--use_hard_negatives", action="store_true")
    return parser


def _finetune_main(args):
    """Classification finetuning dispatch (ref: tasks/glue/finetune.py +
    tasks/race/finetune.py through finetune_utils.finetune)."""
    import dataclasses

    from megatron_llm_tpu.arguments import args_to_configs
    from megatron_llm_tpu.parallel import initialize_parallel
    from megatron_llm_tpu.tokenizer import build_tokenizer
    from megatron_llm_tpu.training.checkpointing import load_checkpoint

    from megatron_llm_tpu.models.classification import (
        Classification,
        MultipleChoice,
    )
    from tasks.finetune_utils import accuracy, finetune

    tokenizer = build_tokenizer(
        args.tokenizer_type or "BertWordPieceLowerCase",
        vocab_file=args.vocab_file,
        make_vocab_size_divisible_by=args.make_vocab_size_divisible_by,
        tensor_parallel_size=args.tensor_model_parallel_size,
    )
    args.model_name = "bert"
    mcfg, pcfg, tcfg, _ = args_to_configs(args, tokenizer.vocab_size)
    mcfg = dataclasses.replace(mcfg, add_binary_head=False)
    assert pcfg.context_parallel_size == 1, (
        "--context_parallel_size: ring attention is causal-only; "
        "encoder finetuning tasks don't support cp"
    )
    initialize_parallel(dp=pcfg.data_parallel_size, pp=1,
                        tp=pcfg.tensor_parallel_size,
                        sequence_parallel=pcfg.sequence_parallel)

    if args.task == "MNLI":
        from tasks.glue.mnli import MNLIDataset as DS

        model = Classification(mcfg, num_classes=3)
    elif args.task == "QQP":
        from tasks.glue.qqp import QQPDataset as DS

        model = Classification(mcfg, num_classes=2)
    else:  # RACE
        from tasks.race.data import RaceDataset as DS

        model = MultipleChoice(mcfg)

    params = model.init(jax.random.key(tcfg.seed))
    # --load is the generic flag the LM-eval path uses; accept it as an
    # alias for --pretrained_checkpoint here
    if not args.pretrained_checkpoint and args.load:
        args.pretrained_checkpoint = args.load
    if args.pretrained_checkpoint:
        # Load ENCODER weights from a BERT pretraining checkpoint; heads
        # stay freshly initialized (the reference's strict=False load,
        # finetune_utils.py:291-312). Orbax restores against the exact
        # saved tree, so restore into a pretraining-shaped template and
        # merge the overlapping subtrees.
        from megatron_llm_tpu.models import BertModel as _Bert

        loaded, errors = None, []
        for binary in (True, False):
            tmpl_cfg = dataclasses.replace(mcfg, add_binary_head=binary)
            tmpl = jax.eval_shape(
                _Bert(tmpl_cfg).init, jax.random.key(0)
            )
            try:
                restored = load_checkpoint(
                    args.pretrained_checkpoint, tmpl, no_load_optim=True,
                    finetune=True,
                )
            except Exception as e:
                errors.append(f"binary_head={binary}: {e!r}")
                continue
            if restored is not None:
                loaded = restored[0]
                break
        assert loaded is not None, (
            f"could not restore encoder weights from "
            f"{args.pretrained_checkpoint}; attempts: {errors}"
        )
        for key in params:
            if key in loaded:
                params[key] = loaded[key]
        print(" > loaded pretrained encoder weights "
              f"({sorted(set(params) & set(loaded))})", flush=True)

    assert args.train_data, f"--train_data is required for {args.task}"
    train_ds = DS("training", args.train_data, tokenizer, mcfg.seq_length)
    valid_ds = (DS("validation", args.valid_data, tokenizer,
                   mcfg.seq_length) if args.valid_data else None)
    params, best = finetune(
        model, params, train_ds, valid_ds, epochs=args.epochs,
        batch_size=args.micro_batch_size, lr=tcfg.lr,
        weight_decay=tcfg.weight_decay, seed=tcfg.seed,
        warmup_fraction=(args.lr_warmup_fraction
                         if args.lr_warmup_fraction is not None else 0.065),
        tcfg=tcfg, log_interval=args.log_interval,
    )
    if valid_ds is not None:
        final = accuracy(model, params, valid_ds, args.micro_batch_size)
        print(f"final validation accuracy: {final:.4f} (best {best:.4f})",
              flush=True)
    if args.save:
        from megatron_llm_tpu.training.checkpointing import save_checkpoint

        save_checkpoint(args.save, 0, params, None, mcfg)
        print(f"saved finetuned weights to {args.save}", flush=True)


def _retriever_eval_main(args):
    """Biencoder retriever accuracy on NQ (ref: tasks/orqa/evaluate_orqa.py
    + evaluate_utils.py): embed the evidence TSV with the context tower,
    embed the questions with the query tower, MIPS on-device, report
    top-k answer-containment accuracy."""
    import dataclasses

    from megatron_llm_tpu.arguments import args_to_configs
    from megatron_llm_tpu.models.biencoder import BiEncoderModel
    from megatron_llm_tpu.parallel import initialize_parallel
    from megatron_llm_tpu.tokenizer import build_tokenizer
    from megatron_llm_tpu.training.checkpointing import load_checkpoint

    from tasks.orqa.evaluate import ORQAEvaluator, read_evidence_tsv

    assert args.evidence_data_path, "--evidence_data_path is required"
    assert args.qa_data_dev or args.qa_data_test, (
        "--qa_data_dev and/or --qa_data_test is required"
    )
    tokenizer = build_tokenizer(
        args.tokenizer_type or "BertWordPieceLowerCase",
        vocab_file=args.vocab_file,
        make_vocab_size_divisible_by=args.make_vocab_size_divisible_by,
        tensor_parallel_size=args.tensor_model_parallel_size,
    )
    args.model_name = "bert"
    mcfg, pcfg, tcfg, _ = args_to_configs(args, tokenizer.vocab_size)
    mcfg = dataclasses.replace(mcfg, add_binary_head=False)
    initialize_parallel(dp=pcfg.data_parallel_size, pp=1,
                        tp=pcfg.tensor_parallel_size)

    model = BiEncoderModel(
        mcfg,
        projection_dim=args.biencoder_projection_dim,
        shared_query_context_model=args.biencoder_shared_query_context_model,
    )
    params = model.init(jax.random.key(tcfg.seed))
    if args.load:
        restored = load_checkpoint(args.load, params, no_load_optim=True,
                                   finetune=True)
        assert restored is not None, f"no checkpoint found in {args.load}"
        params = restored[0]

    evaluator = ORQAEvaluator(
        model, params, tokenizer,
        seq_length=args.retriever_seq_length,
        batch_size=args.micro_batch_size,
    )
    docs = read_evidence_tsv(args.evidence_data_path)
    if args.embedding_path:
        print(f" > loading prebuilt index {args.embedding_path} ...",
              flush=True)
        evaluator.load_index(docs, args.embedding_path)
    else:
        print(f" > embedding {len(docs)} evidence blocks ...", flush=True)
        evaluator.build_index(docs)
    if args.qa_data_dev:
        evaluator.evaluate(args.qa_data_dev, "DEV",
                           topk=args.retriever_topk,
                           match_type=args.match)
    if args.qa_data_test:
        evaluator.evaluate(args.qa_data_test, "TEST",
                           topk=args.retriever_topk,
                           match_type=args.match)


def _retriever_finetune_main(args):
    """Supervised biencoder finetuning on DPR-format NQ
    (ref: tasks/orqa/supervised/finetune.py, RET-FINETUNE-NQ)."""
    import dataclasses

    from megatron_llm_tpu.arguments import args_to_configs
    from megatron_llm_tpu.models.biencoder import BiEncoderModel
    from megatron_llm_tpu.parallel import initialize_parallel
    from megatron_llm_tpu.tokenizer import build_tokenizer
    from megatron_llm_tpu.training.checkpointing import load_checkpoint

    from tasks.orqa.supervised import (
        OpenRetrievalDataset,
        finetune_retriever,
    )

    assert args.train_data, "--train_data (DPR-format json) is required"
    tokenizer = build_tokenizer(
        args.tokenizer_type or "BertWordPieceLowerCase",
        vocab_file=args.vocab_file,
        make_vocab_size_divisible_by=args.make_vocab_size_divisible_by,
        tensor_parallel_size=args.tensor_model_parallel_size,
    )
    args.model_name = "bert"
    mcfg, pcfg, tcfg, _ = args_to_configs(args, tokenizer.vocab_size)
    mcfg = dataclasses.replace(mcfg, add_binary_head=False)
    initialize_parallel(dp=pcfg.data_parallel_size, pp=1,
                        tp=pcfg.tensor_parallel_size)

    model = BiEncoderModel(
        mcfg,
        projection_dim=args.biencoder_projection_dim,
        shared_query_context_model=args.biencoder_shared_query_context_model,
    )
    params = model.init(jax.random.key(tcfg.seed))
    if not args.pretrained_checkpoint and args.load:
        args.pretrained_checkpoint = args.load
    if args.pretrained_checkpoint:
        restored = load_checkpoint(args.pretrained_checkpoint, params,
                                   no_load_optim=True, finetune=True)
        assert restored is not None, (
            f"no checkpoint in {args.pretrained_checkpoint}"
        )
        params = restored[0]

    train_ds = OpenRetrievalDataset(
        args.train_data[0], tokenizer,
        max_seq_length=args.retriever_seq_length,
        use_hard_negatives=args.use_hard_negatives, seed=tcfg.seed,
    )
    valid_ds = (OpenRetrievalDataset(
        args.valid_data[0], tokenizer,
        max_seq_length=args.retriever_seq_length, seed=tcfg.seed)
        if args.valid_data else None)
    params = finetune_retriever(
        model, params, train_ds, valid_ds, epochs=args.epochs,
        batch_size=args.micro_batch_size, lr=tcfg.lr,
        use_hard_negatives=args.use_hard_negatives, seed=tcfg.seed,
        log_interval=args.log_interval,
    )
    if args.save:
        from megatron_llm_tpu.training.checkpointing import save_checkpoint

        save_checkpoint(args.save, 0, params, None, mcfg)
        print(f"saved finetuned retriever to {args.save}", flush=True)


def main(argv=None):
    from megatron_llm_tpu.arguments import args_to_configs, build_base_parser
    from megatron_llm_tpu.parallel import initialize_parallel
    from megatron_llm_tpu.tokenizer import build_tokenizer
    from megatron_llm_tpu.training.checkpointing import load_checkpoint

    from finetune import model_provider
    from tasks.zeroshot.datasets import build_dataset
    from tasks.zeroshot.evaluate import evaluate_and_print_results

    parser = get_tasks_args(build_base_parser())
    args = parser.parse_args(argv)
    if args.task in ("MNLI", "QQP", "RACE"):
        _finetune_main(args)
        print("done :-)")
        return
    if args.task == "MSDP-EVAL-F1":
        # pure file-vs-file metric, no model (ref: tasks/msdp/evaluate.py)
        assert args.guess_file and args.answer_file, (
            "MSDP-EVAL-F1 needs --guess_file and --answer_file"
        )
        from tasks.msdp.evaluate import main as msdp_eval_main

        msdp_eval_main(args)
        print("done :-)")
        return
    if args.task in ("RETRIEVER-EVAL", "ICT-ZEROSHOT-NQ"):
        _retriever_eval_main(args)
        print("done :-)")
        return
    if args.task == "RET-FINETUNE-NQ":
        _retriever_finetune_main(args)
        print("done :-)")
        return
    if args.task == "MSDP-PROMPT":
        assert args.sample_input_file and args.sample_output_file \
            and args.prompt_file and args.prompt_type, (
                "MSDP-PROMPT needs --sample_input_file, "
                "--sample_output_file, --prompt_file, --prompt_type"
            )
    else:
        assert args.valid_data and len(args.valid_data) == 1, \
            "--valid_data takes exactly one path"

    tokenizer = build_tokenizer(
        args.tokenizer_type or "NullTokenizer",
        vocab_file=args.vocab_file,
        merges_file=args.merges_file,
        tokenizer_model=args.tokenizer_model,
        make_vocab_size_divisible_by=args.make_vocab_size_divisible_by,
        tensor_parallel_size=args.tensor_model_parallel_size,
        null_vocab_size=args.null_vocab_size,
    )
    mcfg, pcfg, tcfg, _ = args_to_configs(args, tokenizer.vocab_size)

    initialize_parallel(
        dp=pcfg.data_parallel_size,
        pp=pcfg.pipeline_parallel_size,
        tp=pcfg.tensor_parallel_size,
        cp=pcfg.context_parallel_size,
        sequence_parallel=pcfg.sequence_parallel,
    )

    model = model_provider(args, mcfg)
    params = model.init(jax.random.key(tcfg.seed))
    if args.load:
        restored = load_checkpoint(args.load, params, model_cfg=mcfg,
                                   no_load_optim=True)
        assert restored is not None, f"no checkpoint found in {args.load}"
        params = restored[0]

    if args.task == "MSDP-PROMPT":
        from tasks.msdp.prompt import main as msdp_prompt_main

        msdp_prompt_main(args, model=model, params=params,
                         tokenizer=tokenizer)
        print("done :-)")
        return

    data = build_dataset(
        args.task, args.valid_data[0], tokenizer, mcfg.seq_length,
        overlapping_eval=args.overlapping_eval,
        strict_lambada=args.strict_lambada,
    )
    print(f" > found {len(data)} samples.")
    evaluate_and_print_results(
        args.task, model, params, data,
        micro_batch_size=args.eval_micro_batch_size or args.micro_batch_size,
        log_interval=args.log_interval,
    )
    print("done :-)")


if __name__ == "__main__":
    main()
