"""QQP dataset (ref: tasks/glue/qqp.py)."""

from __future__ import annotations

from tasks.data_utils import clean_text
from tasks.glue.data import GLUEAbstractDataset

LABELS = [0, 1]


class QQPDataset(GLUEAbstractDataset):

    def __init__(self, name, datapaths, tokenizer, max_seq_length,
                 test_label=0):
        self.test_label = test_label
        super().__init__("QQP", name, datapaths, tokenizer, max_seq_length)

    def process_samples_from_single_path(self, filename):
        """TSV: train rows are (id, qid1, qid2, q1, q2, is_duplicate);
        test rows are (id, q1, q2) with no label (ref qqp.py:21-84)."""
        samples = []
        first, is_test = True, False
        drop = 0
        with open(filename) as f:
            for line in f:
                row = line.strip().split("\t")
                if first:
                    first = False
                    is_test = len(row) == 3
                    continue
                if is_test:
                    if len(row) != 3:
                        drop += 1
                        continue
                    uid, text_a, text_b = (int(row[0]), clean_text(row[1]),
                                           clean_text(row[2]))
                    label = self.test_label
                else:
                    if len(row) != 6:
                        drop += 1
                        continue
                    uid = int(row[0].strip())
                    text_a = clean_text(row[3].strip())
                    text_b = clean_text(row[4].strip())
                    label = int(row[-1].strip())
                if not text_a or not text_b or label not in LABELS:
                    drop += 1
                    continue
                samples.append({"text_a": text_a, "text_b": text_b,
                                "label": label, "uid": uid})
        if drop:
            print(f"  >> dropped {drop} malformed rows", flush=True)
        return samples
